"""Churn stress: concurrent picks, pod add/delete storms, pool mutations,
scraper updates — the whole stack must stay consistent (no crashes, no
picks of dead endpoints after quiescence, slots conserved)."""

import random
import threading
import time

import numpy as np

from gie_tpu.datastore import Datastore
from gie_tpu.datastore.objects import EndpointPool, Pod
from gie_tpu.extproc.server import ExtProcError, PickRequest
from gie_tpu.metricsio import MetricsStore
from gie_tpu.sched import Metric, ProfileConfig, Scheduler
from gie_tpu.sched import constants as C
from gie_tpu.sched.batching import BatchingTPUPicker


def test_stack_survives_churn_storm():
    sched = Scheduler(ProfileConfig())
    ms = MetricsStore()
    ds = Datastore(on_slot_reclaimed=lambda s: (sched.evict_endpoint(s),
                                                ms.remove(s)))
    ds.pool_set(EndpointPool({"app": "x"}, [8000, 8001], "default"))
    picker = BatchingTPUPicker(sched, ds, ms, max_wait_s=0.001)
    stop = threading.Event()
    errors: list = []

    def churner(seed: int) -> None:
        rng = random.Random(seed)
        try:
            while not stop.is_set():
                name = f"pod-{rng.randint(0, 15)}"
                if rng.random() < 0.6:
                    ds.pod_update_or_add(Pod(
                        name=name, labels={"app": "x"},
                        ip=f"10.2.{seed}.{rng.randint(1, 200)}"))
                else:
                    ds.pod_delete("default", name)
                for ep in ds.endpoints()[:4]:
                    ms.update(ep.slot, {
                        Metric.QUEUE_DEPTH: rng.randint(0, 50),
                        Metric.KV_CACHE_UTIL: rng.random() * 0.9,
                    })
        except Exception as e:  # pragma: no cover
            errors.append(e)

    def requester(seed: int) -> None:
        rng = random.Random(1000 + seed)
        try:
            while not stop.is_set():
                eps = ds.endpoints()
                if not eps:
                    time.sleep(0.001)
                    continue
                try:
                    res = picker.pick(
                        PickRequest(headers={}, body=b"r%d" % rng.randint(0, 99)),
                        eps,
                    )
                    # The pick must name an endpoint that existed recently.
                    assert ":" in res.endpoint
                except ExtProcError:
                    pass  # races to empty pools are legitimate
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=churner, args=(i,)) for i in range(3)]
    threads += [threading.Thread(target=requester, args=(i,)) for i in range(4)]
    [t.start() for t in threads]
    time.sleep(3.0)
    stop.set()
    [t.join(timeout=10) for t in threads]
    picker.close()
    assert not errors, errors[:3]

    # Quiescent consistency: slots are conserved (every live endpoint has a
    # unique slot; freed slots return to the pool).
    eps = ds.endpoints()
    slots = [e.slot for e in eps]
    assert len(set(slots)) == len(slots)
    assert all(0 <= s < C.M_MAX for s in slots)
    # A final pick routes to a live endpoint.
    if eps:
        res = BatchingTPUPicker(sched, ds, ms, max_wait_s=0.001)
        try:
            out = res.pick(PickRequest(headers={}, body=b"final"), eps)
            assert out.endpoint in {e.hostport for e in eps}
        finally:
            res.close()


def test_fleet_grows_past_512_then_past_m_max():
    """The >512-endpoint story is CHOSEN, not accidental (VERDICT r4 #4):
    crossing 512 pod x rank endpoints migrates scheduler state into the
    1024 bucket and keeps picking; crossing M_MAX degrades gracefully to a
    schedulable subset — the datastore refuses the slot, counts the
    refusal for the endpoint_slot_overflow alert metric, and picks keep
    routing to admitted endpoints. Reference datastore is unbounded
    (pkg/lwepp/datastore/datastore.go:181-193); a fixed-axis device layout
    buys the compiled pick path, so the overflow mode is the documented
    trade."""
    sched = Scheduler(ProfileConfig())
    ms = MetricsStore()
    ds = Datastore(on_slot_reclaimed=lambda s: (sched.evict_endpoint(s),
                                                ms.remove(s)))
    ds.pool_set(EndpointPool({"app": "big"}, [8000, 8001], "default"))
    picker = BatchingTPUPicker(sched, ds, ms, max_wait_s=0.001)
    try:
        # 260 pods x 2 rank ports = 520 endpoints: past the old 512 wall.
        for i in range(260):
            ds.pod_update_or_add(Pod(
                name=f"pod-{i:04d}", labels={"app": "big"},
                ip=f"10.{i // 200}.{(i // 10) % 20}.{i % 10 + 1}"))
        eps = ds.endpoints()
        assert len(eps) == 520
        assert ds.overflow_count() == 0
        res = picker.pick(PickRequest(headers={}, body=b"past-512"), eps)
        assert res.endpoint in {e.hostport for e in eps}
        # The compiled cycle migrated into the 1024 bucket.
        assert picker._m_bucket == 1024

        # Grow past M_MAX: 253 more pods -> 1026 > 1024 slots wanted.
        for i in range(260, 513):
            ds.pod_update_or_add(Pod(
                name=f"pod-{i:04d}", labels={"app": "big"},
                ip=f"10.{i // 200}.{(i // 10) % 20}.{i % 10 + 1}"))
        eps = ds.endpoints()
        # Schedulable subset: exactly M_MAX admitted, refusals counted.
        assert len(eps) == C.M_MAX
        assert ds.overflow_count() == 2
        slots = [e.slot for e in eps]
        assert len(set(slots)) == len(slots)
        assert all(0 <= s < C.M_MAX for s in slots)
        res = picker.pick(PickRequest(headers={}, body=b"past-1024"), eps)
        assert res.endpoint in {e.hostport for e in eps}

        # Churn frees slots -> a refused endpoint re-enters when the watch
        # re-offers it (next event / periodic resync).
        for i in range(4):
            ds.pod_delete("default", f"pod-{i:04d}")
        assert len(ds.endpoints()) == C.M_MAX - 8
        i = 512
        ds.pod_update_or_add(Pod(
            name=f"pod-{i:04d}", labels={"app": "big"},
            ip=f"10.{i // 200}.{(i // 10) % 20}.{i % 10 + 1}"))
        assert len(ds.endpoints()) == C.M_MAX - 6
    finally:
        picker.close()


def test_legacy_checkpoint_without_ot_v_restores(tmp_path):
    """A warm-restart checkpoint written BEFORE the round-5 ot_v field
    must still restore (affinity preserved; the missing dual defaults to
    cold ones) — upgrades must not silently cold-start the scheduler."""
    import numpy as np

    from gie_tpu.sched.types import SchedState
    from gie_tpu.utils.checkpoint import save_pytree

    st = SchedState.init(m=64)
    st = st.replace(assumed_load=st.assumed_load.at[3].set(7.5))
    legacy = {  # exactly the pre-ot_v field set
        "prefix": {"keys": np.asarray(st.prefix.keys),
                   "present": np.asarray(st.prefix.present),
                   "ages": np.asarray(st.prefix.ages)},
        "assumed_load": np.asarray(st.assumed_load),
        "rr": np.asarray(st.rr),
        "tick": np.asarray(st.tick),
    }
    ckpt = str(tmp_path / "legacy-state")
    save_pytree(ckpt, legacy)

    s = Scheduler(ProfileConfig())
    assert s.restore_state(ckpt)
    assert float(s.state.assumed_load[3]) == 7.5
    assert s.state.m == 64
    assert (np.asarray(s.state.ot_v) == 1.0).all()  # cold dual default


def test_legacy_checkpoint_shape_mismatch_returns_false(tmp_path):
    """Cross-field shape validation on the raw-restore path (ADVICE r5
    #1): a corrupted/mixed-layout checkpoint must fail cleanly with
    False, never construct an inconsistent SchedState that blows up
    later inside the jitted cycle with an opaque shape error."""
    import numpy as np

    from gie_tpu.sched.types import SchedState
    from gie_tpu.utils.checkpoint import save_pytree

    st = SchedState.init(m=64)

    def legacy(**overrides):
        base = {
            "prefix": {"keys": np.asarray(st.prefix.keys),
                       "present": np.asarray(st.prefix.present),
                       "ages": np.asarray(st.prefix.ages)},
            "assumed_load": np.asarray(st.assumed_load),
            "rr": np.asarray(st.rr),
            "tick": np.asarray(st.tick),
        }
        for key, val in overrides.items():
            if key.startswith("prefix_"):
                base["prefix"][key[len("prefix_"):]] = val
            else:
                base[key] = val
        return base

    cases = {
        # present width from a DIFFERENT m than assumed_load's (64//32=2)
        "present-width": legacy(
            prefix_present=np.zeros(
                (int(st.prefix.keys.shape[0]), 256 // 32), np.uint32)),
        # ages length disagreeing with keys
        "ages-len": legacy(
            prefix_ages=np.zeros((17,), np.uint32)),
        # present row count disagreeing with keys
        "present-rows": legacy(
            prefix_present=np.zeros((17, 2), np.uint32)),
        # ot_v present but laid out for a different m
        "ot_v-len": legacy(ot_v=np.ones((256,), np.float32)),
    }
    for name, raw in cases.items():
        ckpt = str(tmp_path / name)
        save_pytree(ckpt, raw)
        s = Scheduler(ProfileConfig())
        before = s.state
        assert not s.restore_state(ckpt), name
        assert s.state is before, name  # live state untouched on failure


def test_scheduler_state_checkpoint_roundtrip(tmp_path):
    """Warm-restart: prefix affinity survives a save/restore cycle."""
    from gie_tpu.sched import Weights
    from gie_tpu.utils.testing import make_endpoints, make_requests

    cfg = ProfileConfig(load_decay=0.0)
    w = Weights.default().replace(prefix=np.float32(3.0))
    s1 = Scheduler(cfg, weights=w)
    eps = make_endpoints(4, queue=[1, 1, 1, 1])
    prompt = b"persistent prefix " * 80
    res = s1.pick(make_requests(1, prompts=[prompt + b"a"]), eps)
    home = int(res.indices[0, 0])
    ckpt = str(tmp_path / "sched-state")
    s1.save_state(ckpt)

    s2 = Scheduler(cfg, weights=w)
    assert s2.restore_state(ckpt)
    queue = [0.5] * 4
    queue[home] = 1.0  # every other endpoint slightly better on load
    res2 = s2.pick(make_requests(1, prompts=[prompt + b"b"]),
                   make_endpoints(4, queue=queue))
    assert int(res2.indices[0, 0]) == home  # affinity survived the restart
    assert not Scheduler(cfg).restore_state(str(tmp_path / "missing"))
