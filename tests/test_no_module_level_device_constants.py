"""Guard against the axon 80x-dispatch landmine: a jitted program that
closes over a MODULE-LEVEL jnp array dispatches ~80x slower on this TPU
backend and degrades the whole process (see pickers.NEG history). This
static scan fails if anyone reintroduces one."""

import ast
import pathlib

PKG = pathlib.Path(__file__).resolve().parent.parent / "gie_tpu"


def _module_level_jnp_calls(tree: ast.Module) -> list[str]:
    hits = []
    for node in tree.body:
        targets = []
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        else:
            continue
        for call in ast.walk(value):
            if not isinstance(call, ast.Call):
                continue
            func = call.func
            # jnp.<anything>(...) at module level creates a device array.
            if (isinstance(func, ast.Attribute)
                    and isinstance(func.value, ast.Name)
                    and func.value.id == "jnp"):
                names = [ast.unparse(t) for t in targets]
                hits.append(f"{', '.join(names)} = jnp.{func.attr}(...)")
    return hits


def test_no_module_level_jnp_constants():
    offenders = []
    for path in PKG.rglob("*.py"):
        tree = ast.parse(path.read_text())
        for hit in _module_level_jnp_calls(tree):
            offenders.append(f"{path.relative_to(PKG.parent)}: {hit}")
    assert not offenders, (
        "module-level jnp constants captured into jit dispatch ~80x slower "
        "on the axon backend — use Python/numpy scalars instead:\n"
        + "\n".join(offenders)
    )
