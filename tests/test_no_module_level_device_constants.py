"""Guard against the axon 80x-dispatch landmine: a jitted program that
closes over a MODULE-IMPORT-TIME jnp array dispatches ~80x slower on this
TPU backend and degrades the whole process (see pickers.NEG history). This
static scan fails on any device-array creation that executes at import
time: module-level assignments, class-body assignments, and function
default arguments — under ANY alias of jax.numpy."""

import ast
import pathlib

PKG = pathlib.Path(__file__).resolve().parent.parent / "gie_tpu"


def _jnp_aliases(tree: ast.Module) -> set[str]:
    aliases = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "jax.numpy":
                    aliases.add(a.asname or "jax.numpy")
        elif isinstance(node, ast.ImportFrom):
            if node.module == "jax" and any(a.name == "numpy" for a in node.names):
                for a in node.names:
                    if a.name == "numpy":
                        aliases.add(a.asname or "numpy")
    return aliases


def _calls_jnp(value: ast.AST, aliases: set[str]) -> bool:
    for call in ast.walk(value):
        if not isinstance(call, ast.Call):
            continue
        func = call.func
        if isinstance(func, ast.Attribute):
            base = func.value
            if isinstance(base, ast.Name) and base.id in aliases:
                return True
            # dotted alias like jax.numpy.zeros
            if (isinstance(base, ast.Attribute)
                    and ast.unparse(base) in aliases):
                return True
    return False


def _import_time_values(tree: ast.Module):
    """Yield (description, value-node) pairs evaluated at import time."""
    def from_body(body, where):
        for node in body:
            if isinstance(node, ast.Assign):
                names = ", ".join(ast.unparse(t) for t in node.targets)
                yield f"{where}{names}", node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                yield f"{where}{ast.unparse(node.target)}", node.value
            elif isinstance(node, ast.ClassDef):
                yield from from_body(node.body, f"{where}{node.name}.")
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for d in list(node.args.defaults) + [
                    d for d in node.args.kw_defaults if d is not None
                ]:
                    yield f"{where}{node.name}(default)", d

    yield from from_body(tree.body, "")


def test_no_import_time_jnp_constants():
    offenders = []
    for path in PKG.rglob("*.py"):
        tree = ast.parse(path.read_text())
        aliases = _jnp_aliases(tree)
        if not aliases:
            continue
        for desc, value in _import_time_values(tree):
            if _calls_jnp(value, aliases):
                offenders.append(f"{path.relative_to(PKG.parent)}: {desc}")
    assert not offenders, (
        "import-time jnp device arrays captured into jit dispatch ~80x "
        "slower on the axon backend — use Python/numpy scalars instead:\n"
        + "\n".join(offenders)
    )
