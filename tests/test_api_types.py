"""API validation tests — the CEL/envtest tier analogue (reference
test/cel/inferencepool_test.go:31-136)."""

import pytest

from gie_tpu.api import types as api


def make_pool(**spec_kwargs) -> api.InferencePool:
    spec = dict(
        selector=api.LabelSelector(matchLabels={"app": "vllm"}),
        targetPorts=[api.Port(8000)],
        endpointPickerRef=api.EndpointPickerRef(
            name="epp", port=api.Port(9002)
        ),
    )
    spec.update(spec_kwargs)
    return api.InferencePool(
        metadata=api.ObjectMeta(name="pool", namespace="default"),
        spec=api.InferencePoolSpec(**spec),
    )


def test_valid_pool_passes():
    make_pool().validate()


def test_target_ports_min_max():
    with pytest.raises(api.ValidationError, match="1-8"):
        make_pool(targetPorts=[]).validate()
    with pytest.raises(api.ValidationError, match="1-8"):
        make_pool(targetPorts=[api.Port(3000 + i) for i in range(9)]).validate()


def test_target_ports_unique():
    """CEL: port number must be unique (inferencepool_types.go:78)."""
    with pytest.raises(api.ValidationError, match="unique"):
        make_pool(targetPorts=[api.Port(8000), api.Port(8000)]).validate()


def test_epp_port_required_for_service_kind():
    """CEL: self.kind != 'Service' || has(self.port)
    (inferencepool_types.go:128)."""
    with pytest.raises(api.ValidationError, match="port is required"):
        make_pool(
            endpointPickerRef=api.EndpointPickerRef(name="epp")
        ).validate()
    # Non-Service kind without port is fine.
    make_pool(
        endpointPickerRef=api.EndpointPickerRef(
            name="epp", kind="MyPicker", group="example.com"
        )
    ).validate()


def test_epp_ref_optional():
    """endpointPickerRef is optional at the API level (reference
    InferencePoolMissingEPPRef conformance semantics)."""
    make_pool(endpointPickerRef=None).validate()


def test_failure_mode_enum():
    with pytest.raises(api.ValidationError, match="FailOpen or FailClose"):
        make_pool(
            endpointPickerRef=api.EndpointPickerRef(
                name="epp", port=api.Port(9002), failureMode="Bogus"
            )
        ).validate()


def test_app_protocol_enum():
    """Enum http / kubernetes.io/h2c (inferencepool_types.go:91)."""
    make_pool(appProtocol=api.APP_PROTOCOL_H2C).validate()
    with pytest.raises(api.ValidationError, match="appProtocol"):
        make_pool(appProtocol="grpc").validate()


def test_port_range():
    with pytest.raises(api.ValidationError, match="1-65535"):
        make_pool(targetPorts=[api.Port(0)]).validate()


def test_roundtrip_dict():
    pool = make_pool()
    d = api.pool_to_dict(pool)
    back = api.pool_from_dict(d)
    assert back.spec.selector.matchLabels == {"app": "vllm"}
    assert back.spec.targetPorts[0].number == 8000
    assert back.spec.endpointPickerRef.port.number == 9002
    assert back.spec.endpointPickerRef.failureMode == api.FAIL_CLOSE


def test_parent_status_condition_replace():
    ps = api.ParentStatus()
    ps.set_condition(api.Condition(api.COND_ACCEPTED, "Unknown", api.REASON_PENDING))
    ps.set_condition(api.Condition(api.COND_ACCEPTED, "True", api.REASON_ACCEPTED))
    assert len(ps.conditions) == 1
    assert ps.get_condition(api.COND_ACCEPTED).status == "True"


def test_crd_generation(tmp_path):
    """CRD YAML emission with bundle-version annotation (reference
    pkg/generator/main.go:35-106)."""
    import yaml as _yaml

    from gie_tpu.api import crdgen
    from gie_tpu.version import BUNDLE_VERSION, BUNDLE_VERSION_ANNOTATION

    paths = crdgen.generate(str(tmp_path))
    assert len(paths) == 2
    pool_crd = _yaml.safe_load(open(paths[0]))
    assert pool_crd["metadata"]["name"] == "inferencepools.inference.networking.k8s.io"
    assert pool_crd["metadata"]["annotations"][BUNDLE_VERSION_ANNOTATION] == BUNDLE_VERSION
    spec = pool_crd["spec"]["versions"][0]["schema"]["openAPIV3Schema"]["properties"]["spec"]
    tp = spec["properties"]["targetPorts"]
    assert tp["minItems"] == 1 and tp["maxItems"] == 8
    assert "port number must be unique" in str(tp["x-kubernetes-validations"])
    epp = spec["properties"]["endpointPickerRef"]
    assert "has(self.port)" in str(epp["x-kubernetes-validations"])


def test_typed_client_crud_and_yaml_roundtrip():
    """Typed clientset facade (C3 analogue): CRUD + manifest round trips
    against a FakeCluster store."""
    from gie_tpu.api.client import InferencePoolClient
    from gie_tpu.controller import FakeCluster

    store = FakeCluster()
    client = InferencePoolClient(store)
    pool = make_pool()
    client.apply(pool)
    got = client.get("pool")
    assert got is pool

    text = client.to_yaml(got)
    back = client.from_yaml(text)
    assert back.spec.targetPorts[0].number == 8000
    assert back.spec.endpointPickerRef.name == "epp"

    status = api.InferencePoolStatus()
    ps = api.ParentStatus(parentRef=api.ParentReference(name="gw"))
    ps.set_condition(api.Condition(api.COND_ACCEPTED, "True", api.REASON_ACCEPTED))
    status.parents.append(ps)
    events = []
    store.subscribe(events.append)
    client.update_status(got, status)
    # The status write must COMMIT to the store (watch event observed),
    # not just mutate the local object.
    assert any(e.type == "MODIFIED" and e.name == "pool" for e in events)
    assert client.get("pool").status.parents[0].parentRef.name == "gw"

    client.delete("pool")
    assert client.get("pool") is None

    bad = make_pool(targetPorts=[api.Port(1), api.Port(1)])
    with pytest.raises(api.ValidationError):
        client.apply(bad)
