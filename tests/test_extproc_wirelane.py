"""gie-wire acceptance suite (docs/EXTPROC.md): the zero-protobuf wire
lane against the legacy lane, byte for byte.

Four pins:

1. Byte parity across the PR 5 matrix: every scripted stream produces
   the exact serialized ProcessingResponse sequence the legacy
   (full-parse, fast_lane=False) server emits — classified frames and
   FALLBACK frames alike.
2. Zero materialization: classified headers-only and scanned-body
   admissions construct ZERO ProcessingRequest objects, counted by
   wire.MATERIALIZED (every wire-path FromString funnels through
   wire.materialize).
3. Walker parity under byte mutation: the native walker and the pure-
   Python mirror agree on every mutated frame, and their verdicts are
   sound against the real protobuf parser (classified => FromString
   accepts and the oneof matches; INVALID => FromString raises).
4. Worker-pool graceful drain: an in-flight stream on a draining
   2-worker SO_REUSEPORT pool runs to completion inside the grace
   window with no aborted-stream callback and no leaked active-stream
   gauge charge.
"""

import json
import queue
import random
import time

import pytest

from gie_tpu.extproc import pb, wire
from gie_tpu.extproc.server import (
    RoundRobinPicker,
    ShedError,
    StreamingServer,
)
from tests.test_extproc import body_msg, headers_msg
from tests.test_extproc_fastlane import (
    CHAT,
    COMPLETION,
    REQUEST_HEADERS,
    RecordingPicker,
    extractor_chain,
    make_ds,
    run_stream,
)


def wire_stream(server, messages):
    """Drive serialized frames through a WireSession the way the wire
    service handler does; returns the raw response bytes in order."""
    session = server.wire_session()
    out = []
    try:
        for msg in messages:
            out.extend(session.feed(msg.SerializeToString()))
            if session.done:
                break
    finally:
        session.close(None)
    return out


def both_lanes_wire(messages, *, n=3, grpc_pool=False, chain_fn=None,
                    picker_fn=RecordingPicker):
    """(wire_response_bytes, legacy_response_bytes, wire_picker,
    legacy_picker) for one scripted stream on identically-wired
    servers."""
    ds_w, ds_l = make_ds(n, grpc_pool=grpc_pool), make_ds(n, grpc_pool=grpc_pool)
    pw, pl = picker_fn(), picker_fn()
    wire_srv = StreamingServer(
        ds_w, pw, bbr_chain=chain_fn() if chain_fn else None, fast_lane=True)
    legacy_srv = StreamingServer(
        ds_l, pl, bbr_chain=chain_fn() if chain_fn else None, fast_lane=False)
    got = wire_stream(wire_srv, messages)
    want = [r.SerializeToString() for r in run_stream(legacy_srv, messages)]
    return got, want, pw, pl


def assert_wire_byte_identical(messages, **kw):
    got, want, pw, pl = both_lanes_wire(messages, **kw)
    assert len(got) == len(want), (len(got), len(want))
    for i, (g, w) in enumerate(zip(got, want)):
        assert g == w, (
            f"response {i} differs:\nwire:   "
            f"{pb.ProcessingResponse.FromString(g)}\nlegacy: "
            f"{pb.ProcessingResponse.FromString(w)}")
    return got, want, pw, pl


# --------------------------------------------------------------------------
# 1. Byte parity, classified and fallback paths
# --------------------------------------------------------------------------


def test_wire_parity_headers_only():
    assert_wire_byte_identical([headers_msg(REQUEST_HEADERS)])


def test_wire_parity_body_no_chain():
    assert_wire_byte_identical(
        [headers_msg(REQUEST_HEADERS, end_of_stream=False),
         body_msg(COMPLETION)])


def test_wire_parity_body_with_extractor_chain():
    got, _, pw, pl = assert_wire_byte_identical(
        [headers_msg(REQUEST_HEADERS, end_of_stream=False),
         body_msg(COMPLETION)],
        chain_fn=extractor_chain)
    mut = pb.ProcessingResponse.FromString(
        got[0]).request_headers.response.header_mutation
    keys = {o.header.key: o.header.raw_value for o in mut.set_headers}
    assert keys["X-Gateway-Model-Name"] == b"llama-3.1-8b"
    # The scheduler-visible PickRequests match too, not just the bytes.
    assert pw.requests[-1].model == pl.requests[-1].model


def test_wire_parity_chat_and_chunked_body():
    assert_wire_byte_identical(
        [headers_msg(REQUEST_HEADERS, end_of_stream=False),
         body_msg(CHAT[:9], end_of_stream=False),
         body_msg(CHAT[9:])],
        chain_fn=extractor_chain)


def test_wire_parity_malformed_and_empty_bodies():
    for body in (b"not json", b"", b"[1,2,3]", b'{"model": 5}',
                 b'{"truncated": ', b'\xff\xfe garbage'):
        assert_wire_byte_identical(
            [headers_msg(REQUEST_HEADERS, end_of_stream=False),
             body_msg(body)],
            chain_fn=extractor_chain)


def test_wire_parity_rewrite_applies():
    """A firing rewrite mutates the body: the wire lane emits the same
    CONTINUE_AND_REPLACE chunk stream the legacy lane builds."""
    from gie_tpu.api.modelrewrite import (
        InferenceModelRewrite,
        ModelMatch,
        RewriteEngine,
        RewriteRule,
        TargetModel,
    )
    from gie_tpu.bbr.chain import (
        ModelExtractorPlugin,
        ModelRewritePlugin,
        PluginChain,
    )

    def chain():
        eng = RewriteEngine(seed=0)
        eng.apply(InferenceModelRewrite(
            name="rw", pool_ref="pool",
            rules=[RewriteRule(matches=[ModelMatch("llama-3.1-8b")],
                               targets=[TargetModel("llama-70b")])]))
        return PluginChain([ModelExtractorPlugin(),
                            ModelRewritePlugin(eng, "pool")])

    got, _, _, _ = assert_wire_byte_identical(
        [headers_msg(REQUEST_HEADERS, end_of_stream=False),
         body_msg(COMPLETION)],
        chain_fn=chain)
    body_resp = pb.ProcessingResponse.FromString(got[1]).request_body.response
    assert body_resp.status == pb.CommonResponse.CONTINUE_AND_REPLACE
    assert json.loads(body_resp.body_mutation.body)["model"] == "llama-70b"


def test_wire_parity_transcoding_buffered_and_streaming():
    for body in (COMPLETION, CHAT):
        assert_wire_byte_identical(
            [headers_msg(REQUEST_HEADERS, end_of_stream=False),
             body_msg(body)],
            grpc_pool=True, chain_fn=extractor_chain)


def test_wire_parity_subset_metadata_falls_back():
    """A frame carrying metadata_context never classifies: the wire lane
    materializes it and the subset filter still applies identically."""
    md = {"envoy.lb.subset_hint":
          {"x-gateway-destination-endpoint-subset": "10.0.0.1,10.0.0.2"}}
    before = wire.MATERIALIZED
    got, _, _, _ = assert_wire_byte_identical(
        [headers_msg(REQUEST_HEADERS, metadata_struct=md)])
    assert wire.MATERIALIZED > before  # the fallback really fired
    mut = pb.ProcessingResponse.FromString(
        got[0]).request_headers.response.header_mutation
    dest = {o.header.key: o.header.raw_value for o in mut.set_headers}
    assert dest["x-gateway-destination-endpoint"] in (
        b"10.0.0.1:8000", b"10.0.0.2:8000")


def test_wire_parity_steering_header():
    hdrs = dict(REQUEST_HEADERS)
    hdrs["test-epp-endpoint-selection"] = "10.0.0.2:8000"
    got, _, _, _ = assert_wire_byte_identical([headers_msg(hdrs)])
    mut = pb.ProcessingResponse.FromString(
        got[0]).request_headers.response.header_mutation
    dest = {o.header.key: o.header.raw_value for o in mut.set_headers}
    assert dest["x-gateway-destination-endpoint"] == b"10.0.0.2:8000"


def test_wire_parity_shed():
    class SheddingPicker(RecordingPicker):
        def pick(self, req, candidates):
            raise ShedError()

    got, want, _, _ = both_lanes_wire(
        [headers_msg(REQUEST_HEADERS)], picker_fn=SheddingPicker)
    assert got == want
    resp = pb.ProcessingResponse.FromString(got[0])
    assert resp.immediate_response.status.code == 429


def test_wire_parity_response_phase_sse_counting():
    sse = (b'data: {"choices": [{"text": "a"}]}\n\n'
           b'data: {"choices": [{"text": "b"}]}\n\n'
           b'data: [DONE]\n\n')
    messages = [
        headers_msg(REQUEST_HEADERS, end_of_stream=False),
        body_msg(COMPLETION),
        pb.ProcessingRequest(response_headers=pb.HttpHeaders()),
        pb.ProcessingRequest(response_body=pb.HttpBody(
            body=sse, end_of_stream=True)),
    ]
    tokens = {}
    for lane in ("wire", "legacy"):
        seen = []
        server = StreamingServer(
            make_ds(), RecordingPicker(), fast_lane=(lane == "wire"),
            on_response_complete=lambda ctx: seen.append(ctx.resp_tokens))
        if lane == "wire":
            resp_bytes = wire_stream(server, messages)
        else:
            resp_bytes = [r.SerializeToString()
                          for r in run_stream(server, messages)]
        tokens[lane] = (seen, resp_bytes)
    assert tokens["wire"] == tokens["legacy"]
    assert tokens["wire"][0] == [2]


def test_wire_invalid_frame_fails_like_the_deserializer():
    """Truncated bytes: the legacy lane dies in the request deserializer;
    the wire session must surface the same DecodeError from materialize."""
    from google.protobuf.message import DecodeError

    server = StreamingServer(make_ds(), RecordingPicker(), fast_lane=True)
    session = server.wire_session()
    good = headers_msg(REQUEST_HEADERS).SerializeToString()
    with pytest.raises(DecodeError):
        session.feed(good[:-3])
    session.close(None)


def test_wire_session_requires_fast_lane():
    server = StreamingServer(make_ds(), RecordingPicker(), fast_lane=False)
    with pytest.raises(ValueError, match="fast_lane"):
        server.wire_session()


# --------------------------------------------------------------------------
# 2. Zero materialization on classified admissions
# --------------------------------------------------------------------------


def test_zero_materialization_headers_only_and_scanned_body():
    server = StreamingServer(make_ds(), RecordingPicker(), fast_lane=True,
                             bbr_chain=extractor_chain())
    before = wire.MATERIALIZED
    out = wire_stream(server, [headers_msg(REQUEST_HEADERS)])
    assert len(out) == 1
    out = wire_stream(server, [
        headers_msg(REQUEST_HEADERS, end_of_stream=False),
        body_msg(COMPLETION[:40], end_of_stream=False),
        body_msg(COMPLETION[40:]),
    ])
    assert len(out) == 2  # deferred headers response + body passthrough
    assert wire.MATERIALIZED == before, (
        "classified admission frames materialized a ProcessingRequest")


def test_response_headers_frame_materializes_exactly_once():
    server = StreamingServer(make_ds(), RecordingPicker(), fast_lane=True)
    session = server.wire_session()
    session.feed(headers_msg(REQUEST_HEADERS).SerializeToString())
    before = wire.MATERIALIZED
    session.feed(pb.ProcessingRequest(
        response_headers=pb.HttpHeaders()).SerializeToString())
    assert wire.MATERIALIZED == before + 1
    session.close(None)


# --------------------------------------------------------------------------
# 3. Walker parity under byte mutation (bounded tier-1 fuzz; the deep
#    ASan run lives in test_fuzz_smoke.py / make fuzz-smoke)
# --------------------------------------------------------------------------


def _mutate(rng, data: bytes) -> bytes:
    buf = bytearray(data)
    for _ in range(rng.randint(1, 3)):
        op = rng.randrange(4)
        if op == 0 and buf:
            buf[rng.randrange(len(buf))] = rng.randrange(256)
        elif op == 1:
            buf.insert(rng.randrange(len(buf) + 1), rng.randrange(256))
        elif op == 2 and buf:
            del buf[rng.randrange(len(buf))]
        elif buf:
            i = rng.randrange(len(buf))
            buf[i] ^= 1 << rng.randrange(8)
    return bytes(buf)


_ONEOF_BY_KIND = {2: "request_headers", 3: "request_body",
                  5: "response_headers", 6: "response_body"}


def test_walker_native_python_parity_under_mutation():
    if wire.walk_native(b"") is None:
        pytest.skip("native pbwalk library not built")
    import sys
    sys.path.insert(0, "hack")
    try:
        from fuzz_seeds import PBWALK_SEEDS
    finally:
        sys.path.pop(0)

    rng = random.Random(0x61E)
    checked = 0
    for _ in range(4000):
        data = _mutate(rng, rng.choice(PBWALK_SEEDS))
        native = wire.walk_native(data)
        pure = wire.walk_py(data)
        assert native is not None and tuple(native) == pure, (
            f"walker divergence on {data.hex()}: "
            f"native={native} python={pure}")
        verdict, off, length = pure
        try:
            msg = pb.ProcessingRequest.FromString(data)
        except Exception:
            msg = None
        if verdict == wire.INVALID:
            assert msg is None, (
                f"walker rejected bytes upb accepts: {data.hex()}")
        elif verdict >= 0:
            assert msg is not None, (
                f"walker classified bytes upb rejects: {data.hex()}")
            kind = verdict & 0x07
            which = msg.WhichOneof("request")
            assert which == _ONEOF_BY_KIND.get(kind), (data.hex(), which)
            if verdict & wire.PAYLOAD_BIT and kind in (3, 6):
                body = (msg.request_body if kind == 3
                        else msg.response_body).body
                assert data[off:off + length] == body, data.hex()
            checked += 1
        # FALLBACK makes no claim: upb may accept or reject.
    assert checked > 100, "mutation run went vacuous"


# --------------------------------------------------------------------------
# 4. Worker pool: graceful drain
# --------------------------------------------------------------------------


def test_worker_pool_graceful_drain_finishes_inflight_stream():
    import grpc

    from gie_tpu.extproc.workers import ExtProcWorkerPool
    from gie_tpu.runtime import metrics as own_metrics

    aborted = []
    server = StreamingServer(make_ds(), RoundRobinPicker(), fast_lane=True,
                             on_stream_aborted=lambda ctx: aborted.append(ctx))
    pool = ExtProcWorkerPool(server, 2, wire=True)
    port = pool.bind("127.0.0.1:0")
    pool.start()
    streams_before = own_metrics.REGISTRY.get_sample_value(
        "gie_active_streams") or 0.0

    channel = grpc.insecure_channel(f"127.0.0.1:{port}")
    process = channel.stream_stream(
        "/envoy.service.ext_proc.v3.ExternalProcessor/Process",
        request_serializer=pb.ProcessingRequest.SerializeToString,
        response_deserializer=pb.ProcessingResponse.FromString)

    feed: queue.Queue = queue.Queue()

    def requests():
        while True:
            item = feed.get()
            if item is None:
                return
            yield item

    call = process(requests())
    # Open the stream mid-request: headers in, body still pending. The
    # deferred headers frame produces no response yet, so wait for the
    # in-process active-streams gauge to show the accepted stream (no
    # initial metadata flows before the first response message).
    feed.put(headers_msg(REQUEST_HEADERS, end_of_stream=False))
    deadline = time.monotonic() + 5.0
    while (own_metrics.REGISTRY.get_sample_value("gie_active_streams")
           or 0.0) <= streams_before:
        assert time.monotonic() < deadline, "stream never accepted"
        time.sleep(0.01)

    stopped = pool.stop(grace=10.0)
    # The drain must NOT cut the in-flight stream: finish the whole
    # request AND response phase (response headers seen = the served
    # feedback fired normally, so no aborted-stream release is owed).
    responses = []
    try:
        feed.put(body_msg(COMPLETION))
        responses.append(next(call))  # deferred headers response
        responses.append(next(call))  # body passthrough
        feed.put(pb.ProcessingRequest(response_headers=pb.HttpHeaders()))
        responses.append(next(call))
        feed.put(pb.ProcessingRequest(response_body=pb.HttpBody(
            body=b"done", end_of_stream=True)))
        responses.append(next(call))
    finally:
        feed.put(None)
    assert responses[0].HasField("request_headers")
    assert responses[1].HasField("request_body")
    assert responses[2].HasField("response_headers")
    assert responses[3].HasField("response_body")
    assert stopped.wait(15), "drain never completed"
    channel.close()

    assert aborted == [], "drain aborted an in-flight stream"
    streams_after = own_metrics.REGISTRY.get_sample_value(
        "gie_active_streams") or 0.0
    assert streams_after == streams_before, (
        "active-stream charge leaked across the drain")
    # New RPCs are refused once draining.
    ch2 = grpc.insecure_channel(f"127.0.0.1:{port}")
    proc2 = ch2.stream_stream(
        "/envoy.service.ext_proc.v3.ExternalProcessor/Process",
        request_serializer=pb.ProcessingRequest.SerializeToString,
        response_deserializer=pb.ProcessingResponse.FromString)
    with pytest.raises(grpc.RpcError):
        list(proc2(iter([headers_msg(REQUEST_HEADERS)])))
    ch2.close()


def test_worker_pool_rejects_second_bind_and_bad_worker_count():
    from gie_tpu.extproc.workers import ExtProcWorkerPool

    server = StreamingServer(make_ds(), RoundRobinPicker(), fast_lane=True)
    with pytest.raises(ValueError):
        ExtProcWorkerPool(server, 0)
    pool = ExtProcWorkerPool(server, 1)
    pool.bind("127.0.0.1:0")
    with pytest.raises(RuntimeError):
        pool.bind("127.0.0.1:0")
    pool.stop(grace=0).wait(5)
