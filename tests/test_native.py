"""Native chunker: bit-parity with the Python fallback + speed sanity."""

import time

import numpy as np
import pytest

from gie_tpu.sched import hashing


requires_native = pytest.mark.skipif(
    hashing._NATIVE is None, reason="native/libgiechunker.so not built"
)


@requires_native
def test_native_matches_python_bit_for_bit():
    rng = np.random.default_rng(0)
    prompts = [
        bytes(rng.integers(0, 256, rng.integers(0, 5000), dtype=np.uint8))
        for _ in range(64)
    ] + [b"", b"short", b"x" * 64, b"y" * 63, b"z" * 65]
    native_h, native_c = hashing.batch_chunk_hashes(prompts)
    py_h = np.zeros_like(native_h)
    py_c = np.zeros_like(native_c)
    for i, p in enumerate(prompts):
        py_h[i], py_c[i] = hashing.chunk_hashes(p)
    assert (native_c == py_c).all()
    assert (native_h == py_h).all()


@requires_native
def test_native_is_faster_on_large_batch():
    prompts = [b"SYSTEM PROMPT " * 600 + b"%d" % i for i in range(1024)]
    t0 = time.perf_counter()
    hashing.batch_chunk_hashes(prompts)
    native_t = time.perf_counter() - t0
    t0 = time.perf_counter()
    for p in prompts[:128]:
        hashing.chunk_hashes(p)
    py_t = (time.perf_counter() - t0) * 8  # scale to 1024
    assert native_t < py_t


def test_native_lib_path_variant_selection(monkeypatch):
    """GIE_NATIVE_ASAN selects the sanitizer .so by VALUE: unset and "0"
    both mean the production build (an accidental -asan pick fails to
    load and silently drops every loader to the pure-Python path)."""
    from gie_tpu.utils.nativelib import native_lib_path

    monkeypatch.delenv("GIE_NATIVE_ASAN", raising=False)
    assert native_lib_path("giechunker").endswith("/libgiechunker.so")
    monkeypatch.setenv("GIE_NATIVE_ASAN", "0")
    assert native_lib_path("giechunker").endswith("/libgiechunker.so")
    monkeypatch.setenv("GIE_NATIVE_ASAN", "1")
    assert native_lib_path("giechunker").endswith("/libgiechunker-asan.so")
