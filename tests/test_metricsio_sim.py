"""Metrics protocol + simulator tests (proposal 003 mappings; proposal 006
stub semantics)."""

import time

import pytest

from gie_tpu.metricsio import MetricsStore
from gie_tpu.metricsio.mappings import SGLANG, TRITON_TRTLLM, TRTLLM_SERVE, VLLM
from gie_tpu.metricsio.scrape import Scraper, parse_scrape
from gie_tpu.sched.constants import Metric
from gie_tpu.simulator import StubConfig, VLLMStub
from gie_tpu.utils.lora import LoraRegistry


VLLM_TEXT = """\
# TYPE vllm:num_requests_waiting gauge
vllm:num_requests_waiting 7
# TYPE vllm:num_requests_running gauge
vllm:num_requests_running 3
# TYPE vllm:kv_cache_usage_perc gauge
vllm:kv_cache_usage_perc 0.42
# TYPE vllm:cache_config_info gauge
vllm:cache_config_info{block_size="16",num_gpu_blocks="2048"} 1
# TYPE vllm:lora_requests_info gauge
vllm:lora_requests_info{max_lora="4",running_lora_adapters="a1, a2",waiting_lora_adapters="a3"} 100.0
vllm:lora_requests_info{max_lora="4",running_lora_adapters="old",waiting_lora_adapters=""} 50.0
"""

TRITON_TEXT = """\
# TYPE nv_trt_llm_request_metrics gauge
nv_trt_llm_request_metrics{request_type="waiting"} 5
nv_trt_llm_request_metrics{request_type="scheduled"} 2
# TYPE nv_trt_llm_kv_cache_block_metrics gauge
nv_trt_llm_kv_cache_block_metrics{kv_cache_block_type="fraction"} 0.66
nv_trt_llm_kv_cache_block_metrics{kv_cache_block_type="tokens_per"} 32
nv_trt_llm_kv_cache_block_metrics{kv_cache_block_type="max"} 1024
"""

SGLANG_TEXT = """\
sglang:num_queue_reqs 1
sglang:num_running_reqs 9
sglang:token_usage 0.81
"""


def test_parse_vllm():
    reg = LoraRegistry()
    metrics, active, waiting = parse_scrape(VLLM_TEXT, VLLM, reg)
    assert metrics[Metric.QUEUE_DEPTH] == 7
    assert metrics[Metric.RUNNING_REQUESTS] == 3
    assert metrics[Metric.KV_CACHE_UTIL] == pytest.approx(0.42)
    assert metrics[Metric.BLOCK_SIZE] == 16
    assert metrics[Metric.NUM_BLOCKS] == 2048
    assert metrics[Metric.MAX_LORA] == 4
    # Freshest lora_requests_info series wins (ts 100 > 50).
    assert active == [reg.id_for("a1"), reg.id_for("a2")]
    assert waiting == [reg.id_for("a3")]


def test_parse_triton_labeled_gauges():
    metrics, _, _ = parse_scrape(TRITON_TEXT, TRITON_TRTLLM)
    assert metrics[Metric.QUEUE_DEPTH] == 5
    assert metrics[Metric.RUNNING_REQUESTS] == 2
    assert metrics[Metric.KV_CACHE_UTIL] == pytest.approx(0.66)
    assert metrics[Metric.BLOCK_SIZE] == 32
    assert metrics[Metric.NUM_BLOCKS] == 1024


def test_parse_sglang():
    metrics, _, _ = parse_scrape(SGLANG_TEXT, SGLANG)
    assert metrics[Metric.QUEUE_DEPTH] == 1
    assert metrics[Metric.RUNNING_REQUESTS] == 9
    assert metrics[Metric.KV_CACHE_UTIL] == pytest.approx(0.81)


def test_scraper_poll_loop_fills_store():
    store = MetricsStore()
    texts = {"http://10.0.0.1:8000/metrics": VLLM_TEXT}
    scraper = Scraper(store, interval_s=0.01, fetcher=lambda url: texts[url])
    scraper.attach(3, "http://10.0.0.1:8000/metrics", VLLM)
    deadline = time.time() + 2
    while time.time() < deadline:
        if store._has_data[3]:
            break
        time.sleep(0.01)
    queue_seen = float(store._metrics[3, Metric.QUEUE_DEPTH])
    scraper.close()
    assert queue_seen == 7
    assert not store._has_data[3]  # detach cleared the slot


def test_scraper_survives_fetch_errors():
    store = MetricsStore()

    def bad_fetch(url):
        raise ConnectionError("down")

    scraper = Scraper(store, interval_s=0.01, fetcher=bad_fetch)
    scraper.attach(0, "http://x/metrics", VLLM)
    time.sleep(0.05)
    scraper.close()
    assert not store._has_data[0]


# ---------------------------------------------------------------------------
# Simulator
# ---------------------------------------------------------------------------


def test_stub_processes_request_lifecycle():
    stub = VLLMStub(StubConfig(decode_tokens_per_s=100.0))
    stub.submit(b"x" * 400, decode_tokens=50)
    done = stub.step(5.0)
    assert len(done) == 1
    c = done[0]
    assert c.ttft_s > 0
    assert c.tpot_s == pytest.approx(1 / 100.0, rel=0.3)


def test_stub_queueing_raises_ttft():
    cfg = StubConfig(max_running=1, decode_tokens_per_s=100.0)
    stub = VLLMStub(cfg)
    stub.submit(b"a" * 400, decode_tokens=100)
    stub.submit(b"b" * 400, decode_tokens=100)
    done = stub.step(10.0)
    assert len(done) == 2
    by_id = {c.rid: c for c in done}
    assert by_id[1].queue_s > by_id[0].queue_s
    assert by_id[1].ttft_s > by_id[0].ttft_s


def test_stub_prefix_cache_reduces_ttft():
    cfg = StubConfig(prefill_tokens_per_s=500.0, decode_tokens_per_s=1000.0)
    shared = b"SYSTEM PROMPT " * 64
    s1 = VLLMStub(cfg)
    first = s1.submit(shared + b"q1", decode_tokens=1)
    s1.step(10.0)
    second = s1.submit(shared + b"q2", decode_tokens=1)
    done = s1.step(10.0)
    cold = VLLMStub(cfg)
    cold.submit(shared + b"q2", decode_tokens=1)
    cold_done = cold.step(10.0)
    warm_ttft = [c for c in done if c.rid == second][0].ttft_s
    assert warm_ttft < cold_done[0].ttft_s * 0.5
    assert [c for c in done if c.rid == second][0].hit_fraction > 0.8


def test_stub_lora_loading_and_metrics():
    cfg = StubConfig(max_lora=2, decode_tokens_per_s=1000.0)
    stub = VLLMStub(cfg)
    stub.submit(b"p" * 100, decode_tokens=1, lora="ad1")
    stub.submit(b"p" * 100, decode_tokens=1, lora="ad2")
    stub.step(3.0)
    text = stub.metrics_text()
    metrics, active, waiting = parse_scrape(text, VLLM, LoraRegistry())
    assert metrics[Metric.MAX_LORA] == 2
    assert len(active) == 2


def test_stub_metrics_scrapeable_by_real_parser():
    stub = VLLMStub()
    for i in range(5):
        stub.submit(b"req %d" % i * 50, decode_tokens=200)
    stub.step(0.05)
    metrics, _, _ = parse_scrape(stub.metrics_text(), VLLM)
    assert metrics[Metric.QUEUE_DEPTH] + metrics[Metric.RUNNING_REQUESTS] == 5
    assert 0 <= metrics[Metric.KV_CACHE_UTIL] <= 1
