"""Admission fast lane parity + behavior (ISSUE 5, docs/EXTPROC.md).

The acceptance bar: BYTE-IDENTICAL ProcessingResponse streams between
--extproc-fast-lane on (zero-parse scan, pooled response templates,
needed-keys header copy) and off (the legacy full-parse path), for
non-transcoding AND transcoding requests — headers response, body
mutation, and dynamic metadata alike. Plus the at-most-once parse
contract: the whole request path performs at most one json.loads, zero
on the fast lane.
"""

import json

import pytest

from gie_tpu.api.modelrewrite import (
    InferenceModelRewrite,
    ModelMatch,
    RewriteEngine,
    RewriteRule,
    TargetModel,
)
from gie_tpu.bbr.chain import (
    ModelExtractorPlugin,
    ModelRewritePlugin,
    PluginChain,
)
from gie_tpu.datastore import Datastore
from gie_tpu.datastore.objects import EndpointPool
from gie_tpu.extproc import codec, pb
from gie_tpu.extproc.server import (
    NEEDED_REQUEST_HEADERS,
    PickResult,
    RoundRobinPicker,
    StreamingServer,
    ShedError,
)
from tests.test_datastore import make_pod
from tests.test_extproc import FakeStream, body_msg, headers_msg


def make_ds(n=3, grpc_pool=False):
    ds = Datastore()
    pool = EndpointPool(selector={"app": "vllm"}, target_ports=[8000],
                        namespace="default")
    if grpc_pool:
        pool.app_protocol = "kubernetes.io/h2c"
    ds.pool_set(pool)
    for i in range(n):
        ds.pod_update_or_add(make_pod(name=f"p{i}", ip=f"10.0.0.{i}"))
    return ds


class RecordingPicker(RoundRobinPicker):
    """RoundRobin that records every PickRequest it sees."""

    def __init__(self, extra_headers=None):
        super().__init__()
        self.requests = []
        self.extra_headers = extra_headers

    def pick(self, req, candidates):
        self.requests.append(req)
        result = super().pick(req, candidates)
        if self.extra_headers:
            result.extra_headers = dict(self.extra_headers)
        return result


def run_stream(server, messages):
    stream = FakeStream(list(messages))
    server.process(stream)
    return stream.sent


def both_lanes(messages, *, n=3, grpc_pool=False, chain_fn=None,
               picker_fn=RecordingPicker):
    """Run one scripted stream through a fast and a legacy server wired
    identically (fresh pickers with the same deterministic sequence) and
    return (fast_responses, legacy_responses, fast_picker, legacy_picker).
    """
    out = {}
    for fast in (True, False):
        ds = make_ds(n, grpc_pool=grpc_pool)
        picker = picker_fn()
        server = StreamingServer(
            ds, picker,
            bbr_chain=chain_fn() if chain_fn else None,
            fast_lane=fast,
        )
        out[fast] = (run_stream(server, messages), picker)
    return out[True][0], out[False][0], out[True][1], out[False][1]


def assert_byte_identical(messages, **kw):
    fast, legacy, pf, pl = both_lanes(messages, **kw)
    assert len(fast) == len(legacy)
    for i, (f, l) in enumerate(zip(fast, legacy)):
        assert f.SerializeToString(deterministic=True) == \
            l.SerializeToString(deterministic=True), (
            f"response {i} differs:\nfast:   {f}\nlegacy: {l}")
    return fast, legacy, pf, pl


COMPLETION = json.dumps({
    "model": "llama-3.1-8b", "prompt": "p" * 256,
    "max_tokens": 128, "stream": False,
}).encode()

CHAT = json.dumps({
    "model": "m-chat",
    "messages": [{"role": "user", "content": "hello"}],
    "max_completion_tokens": 64, "stream": True,
}).encode()

REQUEST_HEADERS = {
    "content-type": "application/json",
    "user-agent": "openai-python/1.40.0",
    "cookie": "session=" + "c" * 64,
    "x-request-id": "11111111-2222-3333-4444-555555555555",
    "x-gateway-inference-objective": "standard",
    "x-gateway-inference-fairness-id": "tenant-1",
}


def extractor_chain():
    return PluginChain([ModelExtractorPlugin()])


# --------------------------------------------------------------------------
# Byte parity
# --------------------------------------------------------------------------


def test_parity_headers_only():
    assert_byte_identical([headers_msg(REQUEST_HEADERS)])


def test_parity_body_no_chain():
    assert_byte_identical(
        [headers_msg(REQUEST_HEADERS, end_of_stream=False),
         body_msg(COMPLETION)])


def test_parity_body_with_extractor_chain():
    fast, legacy, pf, pl = assert_byte_identical(
        [headers_msg(REQUEST_HEADERS, end_of_stream=False),
         body_msg(COMPLETION)],
        chain_fn=extractor_chain)
    # The extracted model header must actually be present in the mutation.
    mut = fast[0].request_headers.response.header_mutation
    keys = {o.header.key: o.header.raw_value for o in mut.set_headers}
    assert keys["X-Gateway-Model-Name"] == b"llama-3.1-8b"


def test_parity_chat_body():
    assert_byte_identical(
        [headers_msg(REQUEST_HEADERS, end_of_stream=False), body_msg(CHAT)],
        chain_fn=extractor_chain)


def test_parity_malformed_and_empty_bodies():
    for body in (b"not json", b"", b"[1,2,3]", b'{"model": 5}',
                 b'{"truncated": ', b'\xff\xfe garbage'):
        assert_byte_identical(
            [headers_msg(REQUEST_HEADERS, end_of_stream=False),
             body_msg(body)],
            chain_fn=extractor_chain)


def test_parity_decode_tokens_header_precedence():
    hdrs = dict(REQUEST_HEADERS)
    hdrs["x-gateway-inference-decode-tokens"] = "99"
    fast, legacy, pf, pl = assert_byte_identical(
        [headers_msg(hdrs, end_of_stream=False), body_msg(COMPLETION)],
        chain_fn=extractor_chain)
    # The scheduler-visible hint must match too, not just the wire bytes.
    assert pf.requests[-1].decode_tokens == pl.requests[-1].decode_tokens == 99.0


@pytest.mark.parametrize("body,expected", [
    (json.dumps({"max_tokens": 40}).encode(), 40.0),
    (json.dumps({"max_tokens": 0, "max_completion_tokens": 7}).encode(), 7.0),
    (json.dumps({"max_tokens": True, "max_output_tokens": 3}).encode(), 3.0),
    (json.dumps({"max_tokens": -5}).encode(), 0.0),
    (json.dumps({"max_tokens": 1e400}).encode(), 0.0),   # inf clamps to 0
    (b'{"max_tokens": NaN, "max_output_tokens": 5}', 5.0),
    (json.dumps({"nothing": 1}).encode(), 0.0),
])
def test_decode_tokens_equivalence(body, expected):
    fast, legacy, pf, pl = assert_byte_identical(
        [headers_msg(REQUEST_HEADERS, end_of_stream=False), body_msg(body)])
    assert pf.requests[-1].decode_tokens == expected
    assert pl.requests[-1].decode_tokens == expected


def test_parity_rewrite_noop_stays_fast():
    """A rewrite engine with no matching rule: the scan answers, no parse
    happens, and output matches legacy exactly."""
    def chain():
        eng = RewriteEngine(seed=0)
        eng.apply(InferenceModelRewrite(
            name="rw", pool_ref="other-pool",
            rules=[RewriteRule(matches=[ModelMatch("zzz")],
                               targets=[TargetModel("never")])]))
        return PluginChain([ModelExtractorPlugin(),
                            ModelRewritePlugin(eng, "pool")])

    assert_byte_identical(
        [headers_msg(REQUEST_HEADERS, end_of_stream=False),
         body_msg(COMPLETION)],
        chain_fn=chain)


def test_parity_rewrite_applies_forces_full_parse():
    """A firing rewrite mutates the body: the fast lane must fall back to
    the full chain internally and still emit identical bytes (headers
    response + CONTINUE_AND_REPLACE body chunks)."""
    def chain():
        eng = RewriteEngine(seed=0)
        eng.apply(InferenceModelRewrite(
            name="rw", pool_ref="pool",
            rules=[RewriteRule(matches=[ModelMatch("llama-3.1-8b")],
                               targets=[TargetModel("llama-70b")])]))
        return PluginChain([ModelExtractorPlugin(),
                            ModelRewritePlugin(eng, "pool")])

    fast, legacy, pf, pl = assert_byte_identical(
        [headers_msg(REQUEST_HEADERS, end_of_stream=False),
         body_msg(COMPLETION)],
        chain_fn=chain)
    # The mutated body really flows: a CONTINUE_AND_REPLACE body response.
    body_resp = fast[1].request_body.response
    assert body_resp.status == pb.CommonResponse.CONTINUE_AND_REPLACE
    assert json.loads(body_resp.body_mutation.body)["model"] == "llama-70b"


def test_parity_transcoding_buffered_and_streaming():
    for body in (COMPLETION, CHAT):
        fast, legacy, pf, pl = assert_byte_identical(
            [headers_msg(REQUEST_HEADERS, end_of_stream=False),
             body_msg(body)],
            grpc_pool=True, chain_fn=extractor_chain)
        # The body really was reframed as a gRPC GenerateRequest.
        mutation = fast[1].request_body.response.body_mutation.body
        frames = list(codec.iter_frames(mutation))
        assert len(frames) == 1


def test_parity_transcoding_untranscodable_body_passthrough():
    assert_byte_identical(
        [headers_msg(REQUEST_HEADERS, end_of_stream=False),
         body_msg(b'{"no": "prompt"}')],
        grpc_pool=True, chain_fn=extractor_chain)


def test_parity_subset_metadata_and_steering_header():
    md = {"envoy.lb.subset_hint":
          {"x-gateway-destination-endpoint-subset": "10.0.0.1,10.0.0.2"}}
    assert_byte_identical([headers_msg(REQUEST_HEADERS, metadata_struct=md)])
    hdrs = dict(REQUEST_HEADERS)
    hdrs["test-epp-endpoint-selection"] = "10.0.0.2:8000"
    fast, legacy, pf, pl = assert_byte_identical([headers_msg(hdrs)])
    mut = fast[0].request_headers.response.header_mutation
    dest = {o.header.key: o.header.raw_value for o in mut.set_headers}
    assert dest["x-gateway-destination-endpoint"] == b"10.0.0.2:8000"


def test_parity_shed_and_response_phase():
    class SheddingPicker(RecordingPicker):
        def pick(self, req, candidates):
            raise ShedError()

    out = {}
    for fast in (True, False):
        server = StreamingServer(make_ds(), SheddingPicker(),
                                 fast_lane=fast)
        out[fast] = run_stream(server, [headers_msg(REQUEST_HEADERS)])
    assert [r.SerializeToString(deterministic=True) for r in out[True]] == \
        [r.SerializeToString(deterministic=True) for r in out[False]]
    assert out[True][0].immediate_response.status.code == 429


def test_parity_response_body_passthrough_and_sse_counting():
    """The response phase (SSE token harvest) must behave identically,
    including the shared pass-through response object."""
    sse = (b'data: {"choices": [{"text": "a"}]}\n\n'
           b'data: {"choices": [{"text": "b"}]}\n\n'
           b'data: [DONE]\n\n')
    messages = [
        headers_msg(REQUEST_HEADERS, end_of_stream=False),
        body_msg(COMPLETION),
        pb.ProcessingRequest(response_headers=pb.HttpHeaders()),
        pb.ProcessingRequest(response_body=pb.HttpBody(
            body=sse, end_of_stream=True)),
    ]
    tokens = {}
    for fast in (True, False):
        seen = []
        server = StreamingServer(
            make_ds(), RecordingPicker(), fast_lane=fast,
            on_response_complete=lambda ctx: seen.append(ctx.resp_tokens))
        responses = run_stream(server, messages)
        tokens[fast] = (seen,
                        [r.SerializeToString(deterministic=True)
                         for r in responses])
    assert tokens[True] == tokens[False]
    assert tokens[True][0] == [2]  # two data frames, [DONE] decremented


def test_parity_picker_extra_headers_template_keysets():
    """Different extra-header key sets interleaved: the template pool must
    never bleed one keyset's skeleton into another's response."""
    extras = [
        {},
        {"x-custom-a": "1"},
        {"x-custom-a": "2", "x-custom-b": "zz"},
        {},
        {"x-custom-b": "only-b"},
        {"x-custom-a": "3"},
    ]
    ds_fast, ds_legacy = make_ds(), make_ds()
    fast_srv = StreamingServer(ds_fast, RoundRobinPicker(), fast_lane=True)
    legacy_srv = StreamingServer(ds_legacy, RoundRobinPicker(),
                                 fast_lane=False)
    for extra in extras:
        msgs = [headers_msg(REQUEST_HEADERS, end_of_stream=False),
                body_msg(COMPLETION)]
        outs = []
        for srv in (fast_srv, legacy_srv):
            srv.picker.extra = extra  # noqa: unused — readability only
            orig_pick = RoundRobinPicker.pick

            def pick(req, candidates, _extra=extra, _srv=srv):
                r = orig_pick(_srv.picker, req, candidates)
                r.extra_headers = dict(_extra)
                return r

            srv.picker.pick = pick
            outs.append(run_stream(srv, list(msgs)))
        for f, l in zip(*outs):
            assert f.SerializeToString(deterministic=True) == \
                l.SerializeToString(deterministic=True)


def test_template_pool_is_bounded():
    from gie_tpu.extproc.server import _HeadersTemplatePool

    pool = _HeadersTemplatePool(limit=4)
    for i in range(32):
        resp = pool.build(
            {"x-gateway-destination-endpoint": "1.2.3.4:8000",
             f"x-hostile-{i}": "v"},
            "1.2.3.4:8000",
        )
        mut = resp.request_headers.response.header_mutation
        assert {o.header.key for o in mut.set_headers} == {
            "x-gateway-destination-endpoint", f"x-hostile-{i}"}
    assert len(pool._templates) <= 4


# --------------------------------------------------------------------------
# At-most-once parse contract
# --------------------------------------------------------------------------


def count_parses(monkeypatch):
    calls = {"n": 0}
    real = json.loads

    def counting(*a, **kw):
        calls["n"] += 1
        return real(*a, **kw)

    monkeypatch.setattr(json, "loads", counting)
    return calls


def test_fast_lane_zero_parses(monkeypatch):
    from gie_tpu.extproc import fieldscan

    if not fieldscan.available():
        pytest.skip("native scanner not built")
    server = StreamingServer(make_ds(), RecordingPicker(),
                             bbr_chain=extractor_chain(), fast_lane=True)
    calls = count_parses(monkeypatch)
    run_stream(server, [headers_msg(REQUEST_HEADERS, end_of_stream=False),
                        body_msg(COMPLETION)])
    assert calls["n"] == 0


def test_legacy_lane_single_parse(monkeypatch):
    server = StreamingServer(make_ds(), RecordingPicker(),
                             bbr_chain=extractor_chain(), fast_lane=False)
    calls = count_parses(monkeypatch)
    run_stream(server, [headers_msg(REQUEST_HEADERS, end_of_stream=False),
                        body_msg(COMPLETION)])
    assert calls["n"] == 1


@pytest.mark.parametrize("fast", [True, False])
def test_transcoding_single_parse(fast, monkeypatch):
    """The satellite fix: the gRPC-transcoding path used to json.loads the
    SAME body twice (chain + codec). Now: exactly one parse per request on
    either lane."""
    server = StreamingServer(make_ds(grpc_pool=True), RecordingPicker(),
                             bbr_chain=extractor_chain(), fast_lane=fast)
    calls = count_parses(monkeypatch)
    run_stream(server, [headers_msg(REQUEST_HEADERS, end_of_stream=False),
                        body_msg(COMPLETION)])
    assert calls["n"] == 1


def test_codec_accepts_prepared_parse():
    parsed = json.loads(COMPLETION)
    framed_a = codec.json_to_generate_request(COMPLETION)
    framed_b = codec.json_to_generate_request(COMPLETION, parsed=parsed)
    assert framed_a == framed_b


def test_chain_reparse_failure_clears_current():
    """A plugin emitting an unparsable mutation must not leave a stale
    parsed dict visible downstream (codec would transcode bytes that no
    longer exist)."""
    class BreakerPlugin:
        name = "breaker"

        def execute(self, body, parsed):
            return {}, b"\x00 not json"

    headers, mutated, parsed = PluginChain(
        [ModelExtractorPlugin(), BreakerPlugin()]
    ).execute(COMPLETION)
    assert mutated == b"\x00 not json"
    assert parsed is None


# --------------------------------------------------------------------------
# Fast-lane behavioral specifics
# --------------------------------------------------------------------------


def test_needed_keys_header_filtering():
    """Fast lane: ctx.headers carries only the needed keys; the junk the
    pick never reads (cookies, auth, tracing) stays out. Legacy carries
    everything. Responses are identical either way (other tests)."""
    seen = {}
    for fast in (True, False):
        picker = RecordingPicker()
        server = StreamingServer(make_ds(), picker, fast_lane=fast)
        run_stream(server,
                   [headers_msg(REQUEST_HEADERS, end_of_stream=False),
                    body_msg(COMPLETION)])
        seen[fast] = picker.requests[-1].headers
    assert "cookie" not in seen[True]
    assert "user-agent" not in seen[True]
    assert "cookie" in seen[False]
    assert seen[True]["x-gateway-inference-objective"] == ["standard"]
    assert seen[True]["x-gateway-inference-fairness-id"] == ["tenant-1"]
    for key in seen[True]:
        assert key in NEEDED_REQUEST_HEADERS


def test_needed_headers_constructor_extension():
    picker = RecordingPicker()
    server = StreamingServer(make_ds(), picker, fast_lane=True,
                             needed_headers={"x-my-picker-header"})
    hdrs = dict(REQUEST_HEADERS)
    hdrs["x-my-picker-header"] = "custom"
    run_stream(server, [headers_msg(hdrs, end_of_stream=False),
                        body_msg(COMPLETION)])
    assert picker.requests[-1].headers["x-my-picker-header"] == ["custom"]


def test_duplicate_needed_headers_preserved_in_order():
    hm = pb.HeaderMap()
    for v in ("first", "second"):
        hm.headers.append(pb.HeaderValue(
            key="x-gateway-inference-objective", raw_value=v.encode()))
    hm.headers.append(pb.HeaderValue(
        key="content-type", raw_value=b"application/json"))
    req = pb.ProcessingRequest(request_headers=pb.HttpHeaders(
        headers=hm, end_of_stream=False))
    picker = RecordingPicker()
    server = StreamingServer(make_ds(), picker, fast_lane=True)
    run_stream(server, [req, body_msg(COMPLETION)])
    assert picker.requests[-1].headers["x-gateway-inference-objective"] == \
        ["first", "second"]


def test_request_context_pool_isolation():
    """Recycled contexts must not leak state between streams: a
    transcoding stream followed by a plain stream on the same server."""
    server = StreamingServer(make_ds(grpc_pool=True), RecordingPicker(),
                             fast_lane=True)
    for _ in range(8):
        run_stream(server,
                   [headers_msg(REQUEST_HEADERS, end_of_stream=False),
                    body_msg(CHAT),
                    pb.ProcessingRequest(response_headers=pb.HttpHeaders()),
                    pb.ProcessingRequest(response_body=pb.HttpBody(
                        body=codec.frame(b"\x08\x01"), end_of_stream=True))])
    plain_server = StreamingServer(make_ds(), RecordingPicker(),
                                   fast_lane=True)
    sent = run_stream(plain_server, [headers_msg(REQUEST_HEADERS)])
    assert sent[0].request_headers.response.clear_route_cache


def test_admission_histogram_records_by_lane():
    from gie_tpu.runtime import metrics as own_metrics

    def count(lane):
        for m in own_metrics.ADMISSION_SECONDS.collect():
            for s in m.samples:
                if s.name.endswith("_count") and s.labels.get("lane") == lane:
                    return s.value
        return 0.0

    before_fast, before_legacy = count("fast"), count("legacy")
    for fast in (True, False):
        server = StreamingServer(make_ds(), RecordingPicker(),
                                 fast_lane=fast)
        run_stream(server,
                   [headers_msg(REQUEST_HEADERS, end_of_stream=False),
                    body_msg(COMPLETION)])
    assert count("fast") == before_fast + 1
    assert count("legacy") == before_legacy + 1


def test_options_flag_plumbs_through():
    import argparse

    from gie_tpu.runtime.options import Options

    parser = argparse.ArgumentParser()
    Options.add_flags(parser)
    on = Options.from_args(parser.parse_args(["--pool-name", "p"]))
    off = Options.from_args(parser.parse_args(
        ["--pool-name", "p", "--no-extproc-fast-lane"]))
    assert on.extproc_fast_lane is True
    assert off.extproc_fast_lane is False


def test_header_scan_native_matches_python_loop():
    """Needed-keys extraction: the native wire-walk and the Python loop
    must see the same headers (incl. raw_value-over-value and empty
    raw_value falling back to value)."""
    from gie_tpu.extproc import fieldscan

    if not fieldscan.available():
        pytest.skip("native scanner not built")
    hm = pb.HeaderMap()
    hm.headers.append(pb.HeaderValue(key="content-type",
                                     raw_value=b"application/json"))
    hm.headers.append(pb.HeaderValue(key="cookie", raw_value=b"nope"))
    hm.headers.append(pb.HeaderValue(
        key="x-gateway-inference-objective", value="via-value-field"))
    hm.headers.append(pb.HeaderValue(
        key="x-gateway-inference-fairness-id", value="ignored",
        raw_value=b"raw-wins"))
    spec = fieldscan.HeaderSpec(NEEDED_REQUEST_HEADERS)
    pairs = fieldscan.scan_headers(hm.SerializeToString(), spec)
    assert pairs == [
        ("content-type", "application/json"),
        ("x-gateway-inference-objective", "via-value-field"),
        ("x-gateway-inference-fairness-id", "raw-wins"),
    ]


def test_header_scan_spec_cache_keyed_by_content():
    """Two different specs used back to back on one thread (server
    re-created with different needed_headers): the native per-thread
    parsed-spec cache must re-key on CONTENT — a freed spec buffer can be
    reallocated at the same address for a different key set."""
    from gie_tpu.extproc import fieldscan

    if not fieldscan.headers_available():
        pytest.skip("native scanner not built")
    hm = pb.HeaderMap()
    hm.headers.append(pb.HeaderValue(key="x-a", raw_value=b"va"))
    hm.headers.append(pb.HeaderValue(key="x-b", raw_value=b"vb"))
    raw = hm.SerializeToString()
    for _ in range(3):  # alternate to defeat any one-entry identity cache
        assert fieldscan.scan_headers(
            raw, fieldscan.HeaderSpec({"x-a"})) == [("x-a", "va")]
        assert fieldscan.scan_headers(
            raw, fieldscan.HeaderSpec({"x-b"})) == [("x-b", "vb")]


def test_scanless_chain_skips_the_scan_entirely(monkeypatch):
    """A chain with a plugin lacking execute_scanned must not pay a wasted
    body scan per request: exactly ONE parse (the chain's), zero scans."""
    class OpaquePlugin:
        name = "opaque"

        def execute(self, body, parsed):
            return {"x-opaque": "1"}, None

    from gie_tpu.extproc import fieldscan

    chain = PluginChain([ModelExtractorPlugin(), OpaquePlugin()])
    assert not chain.supports_scan
    scans = {"n": 0}
    real_scan = fieldscan.scan

    def counting_scan(body):
        scans["n"] += 1
        return real_scan(body)

    monkeypatch.setattr(fieldscan, "scan", counting_scan)
    server = StreamingServer(make_ds(), RecordingPicker(), bbr_chain=chain,
                             fast_lane=True)
    calls = count_parses(monkeypatch)
    run_stream(server, [headers_msg(REQUEST_HEADERS, end_of_stream=False),
                        body_msg(COMPLETION)])
    assert scans["n"] == 0
    assert calls["n"] == 1
