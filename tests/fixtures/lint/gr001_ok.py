"""GR001 negatives: every receive in a daemon loop carries an explicit
bound (or opts out of blocking), Condition.wait is exempt (it releases
the lock it waits on and is notify-driven), and blocking calls OUTSIDE
a loop are not the rule's business."""

import queue
import threading


class Loop:
    def __init__(self):
        self._lock = threading.Lock()
        self._cond = threading.Condition()
        self.q = queue.Queue()
        self.stop = threading.Event()
        self.items = []

    def drain_bounded(self):
        while True:
            try:
                item = self.q.get(timeout=0.5)      # bounded
            except queue.Empty:
                if self.stop.is_set():
                    return
                continue
            self.items.append(item)

    def drain_nonblocking(self):
        while not self.stop.wait(0.1):              # positional timeout
            try:
                self.items.append(self.q.get(block=False))
            except queue.Empty:
                pass

    def lock_bounded(self):
        while not self.stop.is_set():
            if self._lock.acquire(timeout=1.0):     # bounded
                try:
                    pass
                finally:
                    self._lock.release()

    def cond_loop(self):
        # Condition.wait is EXEMPT: it releases the lock while waiting
        # and the paired notify under the same lock is its liveness
        # contract — a timeout would only paper over a missing notify.
        with self._cond:
            while not self.items:
                self._cond.wait()
        return self.items[0]

    def one_shot(self):
        # Outside a loop: a single blocking get is a deliberate join
        # point, not a daemon loop that can never observe shutdown.
        return self.q.get()
