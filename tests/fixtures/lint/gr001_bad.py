"""Golden violation: unbounded blocking receives in daemon loops
(GR001) — a bare queue get, an event wait with no timeout, a socket
recv (which has no per-call bound at all), and a declared lock acquired
without a timeout, each inside a ``while`` loop."""

import queue
import socket
import threading


class Loop:
    def __init__(self):
        self._lock = threading.Lock()
        self.q = queue.Queue()
        self.stop = threading.Event()
        # Annotated: `socket.socket` is lowercase, so the constructor
        # heuristic alone would leave the receiver unresolved (and GR001
        # never guesses) — the annotation is what types it.
        self.sock: socket.socket = socket.socket()

    def drain_forever(self):
        while True:
            item = self.q.get()                # GR001: no timeout
            del item

    def wait_forever(self):
        while not self.stop.is_set():
            self.stop.wait()                   # GR001: no timeout

    def recv_forever(self):
        while True:
            data = self.sock.recv(4096)        # GR001: no bound exists
            if not data:
                return

    def lock_forever(self):
        while True:
            self._lock.acquire()               # GR001: no timeout
            try:
                pass
            finally:
                self._lock.release()
