"""Golden negative: the blocking work runs OUTSIDE the critical
section (fetch-then-lock, parse-then-lock), and a Condition waits on
ITSELF while held (the one blocking call whose contract is to release
the lock). Must produce NO GL002."""

import json
import time
import threading


class Worker:
    def __init__(self):
        self._lock = threading.Lock()
        self._cond = threading.Condition()
        self.state = None

    def parse_then_publish(self, payload):
        parsed = json.loads(payload)   # outside the lock
        with self._lock:
            self.state = parsed

    def sleep_between_sections(self):
        with self._lock:
            x = self.state
        time.sleep(0.01)               # outside the lock
        with self._lock:
            return x

    def wait_on_held_condition(self):
        with self._cond:
            self._cond.wait(0.01)      # releases the held lock: exempt
            return self.state

    def spawn_worker(self):
        # The closure's sleep runs when the WORKER runs, not while this
        # lock is held — nested-def bodies are pruned from the summary.
        with self._lock:
            def worker():
                time.sleep(0.5)
            return worker
