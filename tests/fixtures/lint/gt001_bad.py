"""Golden violation: import-time device constants (GT001) — module
level, class body, function default, and an import-time backend query,
under an alias."""

import jax
import jax.numpy as xnp

_TABLE = xnp.zeros((8,))                    # module level: GT001

_DEVICES = jax.device_count()               # backend query: GT001


class Holder:
    SCALE = xnp.ones((4,)) * 2.0            # class body: GT001


def score(x, bias=xnp.zeros((4,))):         # default arg: GT001
    return x + bias
