"""Golden negative: device arrays built lazily inside functions, and
numpy (host) constants at module level. Must produce NO GT001."""

import jax.numpy as jnp
import numpy as np

_HOST_TABLE = np.zeros((8,))    # numpy at import time is fine


def make_table():
    return jnp.zeros((8,))      # device array built at call time


class Holder:
    SCALE = 2.0                 # python scalar

    def table(self):
        return jnp.ones((4,)) * self.SCALE
