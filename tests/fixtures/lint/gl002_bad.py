"""Golden violation: blocking-while-locked (GL002) — a sleep and a JSON
parse directly under a lock, typed-receiver I/O under a lock, a blocking
helper reached through the call graph, and a D2H pull under a lock in a
jax-importing module."""

import http.client
import json
import time
import threading

import jax  # noqa: F401  (activates the [d2h] rules)
import numpy as np


class Conn:
    def __init__(self):
        self._lock = threading.Lock()
        self.conn = http.client.HTTPConnection("localhost", 1)

    def fetch_locked(self):
        with self._lock:
            self.conn.request("GET", "/")      # typed receiver I/O: GL002
            return self.conn.getresponse()     # method denylist: GL002


def slow_helper():
    time.sleep(0.5)


class Worker:
    def __init__(self):
        self._lock = threading.Lock()
        self.state = None

    def parse_locked(self, payload):
        with self._lock:
            time.sleep(0.1)                    # GL002
            self.state = json.loads(payload)   # GL002

    def helper_locked(self):
        with self._lock:
            slow_helper()                      # transitive sleep: GL002

    def d2h_locked(self, device_array):
        with self._lock:
            return np.asarray(device_array)    # device sync: GL002
