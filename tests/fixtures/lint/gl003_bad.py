"""Golden violation: a lock with no rank in lockorder.toml (GL003) —
every new lock must take a declared place in the hierarchy."""

import threading


class Rogue:
    def __init__(self):
        self._unranked = threading.Lock()   # not in lockorder.toml: GL003

    def use(self):
        with self._unranked:
            return 1
