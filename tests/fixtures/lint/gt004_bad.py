"""Golden violation: host syncs in the mesh/sharding layer (GT004) —
the sharded cycle is an async dispatch end to end; a D2H sync stalls
every chip of the mesh at pick cadence (docs/MESH.md)."""

import jax
import jax.numpy as jnp


def pull_picks(result):
    return jax.device_get(result.indices)            # GT004


def wait_for_state(state):
    state.assumed_load.block_until_ready()           # GT004
    return state


def scalarize(duals):
    return duals.item()                              # GT004


def listify(duals):
    return jnp.cumsum(duals).tolist()                # GT004
