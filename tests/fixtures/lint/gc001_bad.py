"""Golden violation: direct clock calls in a clock-governed module
(GC001) — a monotonic read in a dwell check, a wall-clock read in a
window prune, a sleep in a retry loop, and a module-level clock pin.
Each must route through the Clock seam (gie_tpu/runtime/clock.py)."""

import time

STARTED_AT = time.monotonic()          # GC001: module-level clock pin


class Breaker:
    def __init__(self):
        self.opened_at = 0.0

    def allow(self):
        return time.monotonic() - self.opened_at > 2.0   # GC001

    def window_floor(self):
        return time.time() - 10.0                        # GC001

    def retry(self, fn):
        for _ in range(3):
            try:
                return fn()
            except OSError:
                time.sleep(0.1)                          # GC001
        return None
