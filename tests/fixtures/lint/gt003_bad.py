"""Golden violation: explicit host sync in production code (GT003) —
block_until_ready belongs in bench/test paths."""

import jax.numpy as jnp


def warm(table):
    out = jnp.sum(table)
    out.block_until_ready()          # GT003
    return out


def warm_functional(table):
    import jax

    return jax.block_until_ready(jnp.sum(table))   # GT003
