"""Golden violation: blocking calls inside async code (GA001) — a
synchronous sleep, blocking HTTP through a helper, and a threading
Event wait, all stalling the event loop."""

import time
import threading
import urllib.request


def fetch_sync(url):
    return urllib.request.urlopen(url)      # blocking I/O


class Loop:
    def __init__(self):
        self._lock = threading.Lock()
        self.ready = threading.Event()

    async def handle(self, url):
        time.sleep(0.1)                     # GA001
        body = fetch_sync(url)              # transitive urlopen: GA001
        self.ready.wait()                   # Event wait: GA001
        return body
