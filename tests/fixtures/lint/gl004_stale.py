"""Golden violation: the fixture config ranks ``gl004_stale.Gone._lock``
but this module defines no such lock (GL004) — the declared hierarchy
must describe the code that exists."""


class Gone:
    # The class survived a refactor; its _lock did not. The stale rank
    # entry in lockorder.toml must be deleted with it.
    def __init__(self):
        self.state = None
