"""Golden negative: awaitable forms inside async code — asyncio.sleep,
awaited helpers, and a short lock-protected section. (Note json.loads is
deliberately absent: the shared denylist flags it even in async code —
a large parse stalls the loop exactly like I/O.) Must produce NO
GA001."""

import asyncio
import threading


class Loop:
    def __init__(self):
        self._lock = threading.Lock()
        self.ready = threading.Event()
        self.state = None

    async def tick(self):
        await asyncio.sleep(0.1)            # awaitable sleep
        return self.state

    async def handle(self, payload):
        parsed = payload.decode("utf-8")    # cheap transform is fine
        with self._lock:                    # short section is fine
            self.state = parsed
        self.ready.set()                    # Event.SET never blocks
        return await self.tick()
