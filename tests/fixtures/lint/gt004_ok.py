"""Negative fixture for GT004: mesh construction's host bookkeeping
(numpy over the device LIST, shape reads, sharding trees) touches no
device buffers and is legal in gie_tpu.parallel."""

import jax
import numpy as np


def build_grid(n):
    devices = jax.devices()[:n]
    return np.asarray(devices).reshape(n // 2, 2)    # host objects, fine


def dp_axis(mesh):
    return int(mesh.shape["dp"])                     # static shape read


def spec_width(x):
    return np.ndim(x)                                # structural, no pull
