"""Golden violation: host syncs / Python side effects inside jit-traced
code (GT002) — float() on a traced value, print(), a numpy pull, a
wall-clock read, .item(), and the same in a function only REACHED from
a jitted one."""

import time

import jax
import numpy as np


def _leaf(x):
    print("tracing", x)          # side effect in traced code: GT002
    return float(x) * 2.0        # host sync on a tracer: GT002


@jax.jit
def score(x):
    t = time.time()              # baked into the trace: GT002
    host = np.asarray(x)         # D2H pull: GT002
    v = x.sum().item()           # host sync: GT002
    return _leaf(x) + host.sum() + v + t


def plain(y):
    # Not jitted and not called from jit: none of these fire GT002.
    print("host-side", y)
    return float(y)
