"""Negative: clock-governed code routed through the Clock seam — an
injected clock callable, a now= parameter, and a clock= DEFAULT-ARG
REFERENCE (a reference pins nothing; only a call does). GC001 must stay
silent."""

import time


def _default_now():
    return 0.0


class Breaker:
    def __init__(self, clock=_default_now):
        self.clock = clock
        self.opened_at = 0.0

    def allow(self, now=None):
        now = self.clock() if now is None else now
        return now - self.opened_at > 2.0

    def window_floor(self, now):
        return now - 10.0


def make_breaker(clock=time.monotonic):
    # The reference is allowed: the caller's clock (virtual or real)
    # decides the timeline, not this module.
    return Breaker(clock=clock)
