"""Golden negative: trace-safe jitted code — static-shape reads through
float()/int(), jax.debug.print for tracing, pure jnp math. Must produce
NO GT002."""

import jax
import jax.numpy as jnp


@jax.jit
def score(x):
    scale = float(x.shape[0])        # static property: safe
    n = int(x.ndim)                  # static property: safe
    jax.debug.print("n={n}", n=n)    # the traced-side print
    return jnp.sum(x) * scale


def host_wrapper(batch):
    # Host-side code around the jit boundary may sync freely.
    return float(score(batch).sum())
