"""Golden violation: out-of-order lock acquisition (GL001), three
shapes — direct nesting, nesting through a resolved call chain, and
self-deadlock on a non-reentrant Lock."""

import threading


class Helper:
    def __init__(self):
        self._lock = threading.Lock()   # rank 30

    def touch(self):
        with self._lock:
            return 1


class Outer:
    def __init__(self):
        self._outer = threading.Lock()  # rank 10
        self._inner = threading.Lock()  # rank 20
        self.helper = Helper()

    def inverted_direct(self):
        with self._inner:               # rank 20 held...
            with self._outer:           # ...rank 10 acquired: GL001
                return 1

    def call_chain_inversion(self):
        with self.helper._lock:         # rank 30 held...
            self.ordered()              # ...calls into rank 10: GL001

    def ordered(self):
        with self._outer:
            return 2

    def self_deadlock(self):
        with self._outer:
            with self._outer:           # non-reentrant Lock: GL001
                return 3

    def inverted_one_statement(self):
        with self._inner, self._outer:  # 20 then 10 in ONE with: GL001
            return 4
