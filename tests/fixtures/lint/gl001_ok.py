"""Golden negative: well-ordered acquisition — outer (rank 10) wraps
inner (rank 20), and the cross-call form follows the same order. Must
produce NO GL001."""

import threading


class Outer:
    def __init__(self):
        self._outer = threading.Lock()  # rank 10
        self._inner = threading.Lock()  # rank 20

    def nested_in_order(self):
        with self._outer:
            with self._inner:
                return 1

    def inner_section(self):
        with self._inner:
            return 2

    def call_in_order(self):
        with self._outer:
            return self.inner_section()

    def sequential_not_nested(self):
        with self._inner:
            x = 1
        with self._outer:   # sequential re-ordering is legal
            return x

    def one_statement_in_order(self):
        with self._outer, self._inner:  # 10 then 20 in one with: legal
            return 3
