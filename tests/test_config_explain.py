"""Declarative scheduler config (0845 config API) + explain debug surface."""

import numpy as np
import pytest

from gie_tpu.sched import ProfileConfig, Scheduler
from gie_tpu.sched import constants as C
from gie_tpu.sched.config import load_scheduler_config
from gie_tpu.utils.testing import make_endpoints, make_requests


def test_yaml_config_roundtrip():
    cfg, weights = load_scheduler_config("""
picker: sinkhorn
queue_limit: 200
load_decay: 0.9
plugins:
  prefix: true
  lora: false
weights:
  queue: 2.0
  prefix: 4.0
  assumed_load: 1.5
""")
    assert cfg.picker == "sinkhorn"
    assert cfg.queue_limit == 200
    assert cfg.load_decay == 0.9
    assert cfg.enable_prefix and not cfg.enable_lora
    assert float(weights.queue) == 2.0
    assert float(weights.prefix) == 4.0
    assert float(weights.kv_cache) == 1.0  # untouched default
    # The loaded pair drives a real scheduler.
    sched = Scheduler(cfg, weights=weights)
    res = sched.pick(make_requests(2), make_endpoints(3, queue=[0, 1, 2]))
    assert (np.asarray(res.indices[:, 0]) >= 0).all()


def test_unknown_keys_fail_loudly():
    with pytest.raises(ValueError, match="unknown scheduler config key"):
        load_scheduler_config("qeue_limit: 10")
    with pytest.raises(ValueError, match="unknown plugin"):
        load_scheduler_config("plugins: {prefx: true}")
    with pytest.raises(ValueError, match="unknown weight"):
        load_scheduler_config("weights: {quque: 1}")
    with pytest.raises(ValueError, match="mapping"):
        load_scheduler_config("- a\n- b")


def test_empty_config_is_defaults():
    cfg, weights = load_scheduler_config("")
    assert cfg == ProfileConfig()


def test_explain_decomposes_the_pick():
    sched = Scheduler(ProfileConfig())
    eps = make_endpoints(3, queue=[0, 30, 60], kv=[0.1, 0.5, 0.9])
    reqs = make_requests(2, subset=[[0, 1, 2], [1]])
    out = sched.explain(reqs, eps)
    assert set(out) >= {"queue", "kv_cache", "assumed_load", "prefix", "lora",
                        "total", "mask"}
    assert out["total"].shape == (2, C.M_MAX)
    # Queue column ranks endpoint 0 best; total agrees for request 0.
    assert out["queue"][0, 0] > out["queue"][0, 1] > out["queue"][0, 2]
    assert np.argmax(np.where(out["mask"][0], out["total"][0], -1e9)) == 0
    # Request 1 is pinned to endpoint 1 by its subset mask.
    assert out["mask"][1, 1] and not out["mask"][1, 0]
    # Explain must not mutate scheduler state.
    assert int(sched.state.tick) == 0


def test_explain_matches_actual_pick():
    sched = Scheduler(ProfileConfig())
    eps = make_endpoints(4, queue=[5, 0, 9, 3])
    reqs = make_requests(3)
    out = sched.explain(reqs, eps)
    res = sched.pick(reqs, eps)
    for i in range(3):
        best = int(np.argmax(np.where(out["mask"][i], out["total"][i], -1e9)))
        assert int(res.indices[i, 0]) == best


def test_tuned_profile_matches_committed_yaml():
    """tuned_profile() and config/scheduler/sinkhorn-tuned.yaml are two
    statements of the production default — they must never drift."""
    import dataclasses
    import os

    from gie_tpu.sched.config import load_scheduler_config_file, tuned_profile

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    cfg_yaml, w_yaml = load_scheduler_config_file(
        os.path.join(repo, "config", "scheduler", "sinkhorn-tuned.yaml"))
    cfg_code, w_code = tuned_profile()
    assert cfg_yaml == cfg_code
    for f in dataclasses.fields(w_yaml):
        assert float(getattr(w_yaml, f.name)) == float(getattr(w_code, f.name)), f.name
