"""Worker for the two-process leader-election test (not collected)."""
import sys
import time

from gie_tpu.runtime.leader import LeaseFileElector

lease, seconds = sys.argv[1], float(sys.argv[2])
e = LeaseFileElector(lease, lease_ttl_s=1.0, renew_interval_s=0.1)
e.start()
deadline = time.time() + seconds
while time.time() < deadline:
    print(f"LEADER={int(e.is_leader())} t={time.time():.2f}", flush=True)
    time.sleep(0.2)
e.stop()
