"""Golden-bytes wire-compatibility tests for envoy.service.ext_proc.v3.

Round 1 shipped a look-alike proto whose field numbers diverged from
Envoy's ext-proc v3 (response_headers 4-vs-5, immediate_response 5-vs-7,
HeaderValue.raw_value 2-vs-3, HttpHeaders.end_of_stream 2-vs-3, uint32
status vs HttpStatus) — no real proxy could speak to the EPP. These tests
pin the wire format with bytes CONSTRUCTED BY HAND from the published
protocol's field numbers and wire types (tag = field_number << 3 | wtype),
deliberately independent of this repo's generated descriptors: if the
committed protos ever drift from Envoy again, the goldens fail.

Protocol constants match what the reference consumes via go-control-plane
(reference pkg/lwepp/handlers/server.go:26, go.mod:8) and the normative
spec (reference docs/proposals/004-endpoint-picker-protocol/README.md).
"""

import pytest

from gie_tpu.extproc import StreamingServer, RoundRobinPicker, metadata as mdkeys, pb
from gie_tpu.extproc.envoy import (
    extract_metadata_values,
    get_header_value,
    make_immediate_response,
)

from tests.test_extproc import FakeStream, make_ds

# --------------------------------------------------------------------- #
# Minimal wire codec (protobuf encoding spec, not our descriptors).
# --------------------------------------------------------------------- #

VARINT, I64, LEN, I32 = 0, 1, 2, 5


def varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        out.append(b | (0x80 if n else 0))
        if not n:
            return bytes(out)


def tag(field: int, wtype: int) -> bytes:
    return varint((field << 3) | wtype)


def ld(field: int, payload: bytes) -> bytes:
    """Length-delimited field."""
    return tag(field, LEN) + varint(len(payload)) + payload


def vi(field: int, value: int) -> bytes:
    return tag(field, VARINT) + varint(value)


def decode_fields(data: bytes) -> list:
    """Flat (field_number, wire_type, value) list for one message level."""
    out, i = [], 0
    while i < len(data):
        t, i = _read_varint(data, i)
        field, wtype = t >> 3, t & 7
        if wtype == VARINT:
            v, i = _read_varint(data, i)
        elif wtype == LEN:
            n, i = _read_varint(data, i)
            v = data[i : i + n]
            i += n
        elif wtype == I64:
            v, i = data[i : i + 8], i + 8
        elif wtype == I32:
            v, i = data[i : i + 4], i + 4
        else:  # pragma: no cover - malformed
            raise ValueError(f"bad wire type {wtype}")
        out.append((field, wtype, v))
    return out


def _read_varint(data: bytes, i: int):
    shift = n = 0
    while True:
        b = data[i]
        i += 1
        n |= (b & 0x7F) << shift
        if not b & 0x80:
            return n, i
        shift += 7


def field(fields, number):
    hits = [v for f, _, v in fields if f == number]
    assert hits, f"field {number} absent (have {[f for f, _, _ in fields]})"
    return hits[0]


# --------------------------------------------------------------------- #
# Golden requests: bytes a real Envoy would send.
# --------------------------------------------------------------------- #

def header_value_bytes(key: str, *, value: str = "", raw: bytes = b"") -> bytes:
    # envoy.config.core.v3.HeaderValue: key=1, value=2 (string), raw_value=3 (bytes)
    out = ld(1, key.encode())
    if value:
        out += ld(2, value.encode())
    if raw:
        out += ld(3, raw)
    return out


def header_map_bytes(*header_values: bytes) -> bytes:
    return b"".join(ld(1, hv) for hv in header_values)  # headers = 1


def http_headers_bytes(hmap: bytes, end_of_stream: bool) -> bytes:
    # HttpHeaders: headers=1, end_of_stream=3 (round-1 bug had 2)
    out = ld(1, hmap)
    if end_of_stream:
        out += vi(3, 1)
    return out


def struct_string_value(s: str) -> bytes:
    # google.protobuf.Value{string_value=3}
    return ld(3, s.encode())


def struct_with_field(key: str, value_bytes: bytes) -> bytes:
    # google.protobuf.Struct{fields=1 map<string,Value>}
    return ld(1, ld(1, key.encode()) + ld(2, value_bytes))


def metadata_context_bytes(namespace: str, struct_bytes: bytes) -> bytes:
    # envoy.config.core.v3.Metadata{filter_metadata=1 map<string,Struct>}
    return ld(1, ld(1, namespace.encode()) + ld(2, struct_bytes))


GOLDEN_REQUEST_HEADERS = ld(  # ProcessingRequest.request_headers = 2
    2,
    http_headers_bytes(
        header_map_bytes(
            header_value_bytes(":path", raw=b"/v1/completions"),
            header_value_bytes("x-model", value="llama"),  # string form, field 2
        ),
        end_of_stream=True,
    ),
)

GOLDEN_RESPONSE_HEADERS = ld(  # ProcessingRequest.response_headers = 5 (round-1: 4)
    5,
    http_headers_bytes(
        header_map_bytes(header_value_bytes(":status", raw=b"200")),
        end_of_stream=True,
    ),
) + ld(  # metadata_context = 8, envoy.lb served echo (004 README:84-101)
    8,
    metadata_context_bytes(
        "envoy.lb",
        struct_with_field(
            "x-gateway-destination-endpoint-served",
            struct_string_value("10.0.0.1:8000"),
        ),
    ),
)

GOLDEN_REQUEST_TRAILERS = ld(  # ProcessingRequest.request_trailers = 4 (round-1
    4,  # misparsed this as its response_headers)
    ld(1, header_map_bytes(header_value_bytes("grpc-status", raw=b"0"))),
)

GOLDEN_SUBSET_HINT = GOLDEN_REQUEST_HEADERS + ld(
    8,
    metadata_context_bytes(
        "envoy.lb",
        struct_with_field(
            "x-gateway-destination-endpoint-subset",
            struct_string_value("10.0.0.1"),
        ),
    ),
)


# --------------------------------------------------------------------- #
# Parse side: real-Envoy bytes -> our messages.
# --------------------------------------------------------------------- #

def test_parse_request_headers_golden():
    req = pb.ProcessingRequest.FromString(GOLDEN_REQUEST_HEADERS)
    assert req.WhichOneof("request") == "request_headers"
    assert req.request_headers.end_of_stream is True
    values = {h.key: get_header_value(h) for h in req.request_headers.headers.headers}
    assert values == {":path": "/v1/completions", "x-model": "llama"}


def test_parse_response_headers_not_trailers():
    """Field 5 is response_headers; round 1 parsed these bytes as the wrong
    message type entirely (its response_headers sat at 4 = trailers)."""
    req = pb.ProcessingRequest.FromString(GOLDEN_RESPONSE_HEADERS)
    assert req.WhichOneof("request") == "response_headers"
    assert req.response_headers.end_of_stream is True
    md = extract_metadata_values(req)
    assert md["envoy.lb"]["x-gateway-destination-endpoint-served"] == "10.0.0.1:8000"


def test_parse_request_trailers_distinct():
    req = pb.ProcessingRequest.FromString(GOLDEN_REQUEST_TRAILERS)
    assert req.WhichOneof("request") == "request_trailers"
    assert req.request_trailers.trailers.headers[0].key == "grpc-status"


def test_header_value_string_field_survives():
    """Envoy may send value (field 2, string) instead of raw_value; round 1
    read field 2 as bytes raw_value and silently lost real raw_values."""
    hv = pb.HeaderValue.FromString(header_value_bytes("k", value="v"))
    assert get_header_value(hv) == "v"
    hv = pb.HeaderValue.FromString(header_value_bytes("k", raw=b"raw"))
    assert get_header_value(hv) == "raw"


def test_unknown_upstream_fields_skipped():
    """A newer Envoy sending fields we reserved (attributes=9,
    observability_mode=10) must not break parsing."""
    data = GOLDEN_REQUEST_HEADERS + ld(9, ld(1, b"attr")) + vi(10, 1)
    req = pb.ProcessingRequest.FromString(data)
    assert req.WhichOneof("request") == "request_headers"


# --------------------------------------------------------------------- #
# Emit side: our bytes -> what a real Envoy expects.
# --------------------------------------------------------------------- #

def run_stream(messages):
    ds = make_ds(3)
    srv = StreamingServer(ds, RoundRobinPicker())
    stream = FakeStream(messages)
    srv.process(stream)
    return stream


def test_emitted_headers_response_tags():
    stream = run_stream([pb.ProcessingRequest.FromString(GOLDEN_REQUEST_HEADERS)])
    raw = stream.sent[0].SerializeToString()
    top = decode_fields(raw)
    # ProcessingResponse.request_headers = 1, dynamic_metadata = 8.
    hdr = field(top, 1)
    assert field(top, 8)  # dynamic metadata present
    common = field(decode_fields(hdr), 1)  # HeadersResponse.response = 1
    cfields = decode_fields(common)
    assert field(cfields, 5) == 1  # clear_route_cache = 5 (varint true)
    mutation = field(cfields, 2)  # header_mutation = 2
    # set_headers = 1 -> HeaderValueOption.header = 1 -> key=1/raw_value=3
    opts = [v for f, _, v in decode_fields(mutation) if f == 1]
    seen = {}
    for opt in opts:
        hv = decode_fields(field(decode_fields(opt), 1))
        seen[field(hv, 1).decode()] = field(hv, 3).decode()
    assert mdkeys.DESTINATION_ENDPOINT_KEY in seen
    assert ":" in seen[mdkeys.DESTINATION_ENDPOINT_KEY]


def test_emitted_immediate_response_tags():
    """429 shed must serialize as immediate_response=7 carrying an
    HttpStatus MESSAGE at field 1 with code=429 (round 1: field 5 with a
    bare uint32 — a real Envoy would have read it as response_body)."""
    resp = pb.ProcessingResponse(
        immediate_response=make_immediate_response(429, details="request shed")
    )
    top = decode_fields(resp.SerializeToString())
    imm = decode_fields(field(top, 7))
    status = decode_fields(field(imm, 1))
    assert field(status, 1) == 429  # HttpStatus.code = 1
    assert field(imm, 5) == b"request shed"  # details = 5


def test_emitted_response_path_tags():
    """ProcessingResponse.response_headers = 4 and response_body = 5
    (round 1 emitted 3 and 4 — real Envoy would read request_trailers /
    response_headers)."""
    stream = run_stream(
        [
            pb.ProcessingRequest.FromString(GOLDEN_REQUEST_HEADERS),
            pb.ProcessingRequest.FromString(GOLDEN_RESPONSE_HEADERS),
            pb.ProcessingRequest(
                response_body=pb.HttpBody(body=b"tok", end_of_stream=True)
            ),
        ]
    )
    kinds = [r.WhichOneof("response") for r in stream.sent]
    assert kinds == ["request_headers", "response_headers", "response_body"]
    hdr_top = decode_fields(stream.sent[1].SerializeToString())
    assert field(hdr_top, 4)  # response_headers = 4
    body_top = decode_fields(stream.sent[2].SerializeToString())
    assert field(body_top, 5)  # response_body = 5


def test_full_loop_on_golden_bytes_with_subset():
    """The complete Process choreography driven purely by hand-built wire
    bytes: subset hint (envoy.lb metadata) constrains the pick, the served
    echo feeds back, trailers are tolerated."""
    served = []
    ds = make_ds(3)
    srv = StreamingServer(ds, RoundRobinPicker(), on_served=lambda hp, ctx: served.append(hp))
    stream = FakeStream(
        [
            pb.ProcessingRequest.FromString(GOLDEN_SUBSET_HINT),
            pb.ProcessingRequest.FromString(GOLDEN_REQUEST_TRAILERS),
            pb.ProcessingRequest.FromString(GOLDEN_RESPONSE_HEADERS),
        ]
    )
    srv.process(stream)
    kinds = [r.WhichOneof("response") for r in stream.sent]
    assert kinds == ["request_headers", "response_headers"]
    # Subset hint restricted candidates to 10.0.0.1.
    mutation = stream.sent[0].request_headers.response.header_mutation
    dest = {
        o.header.key: get_header_value(o.header) for o in mutation.set_headers
    }[mdkeys.DESTINATION_ENDPOINT_KEY]
    assert dest.startswith("10.0.0.1:")
    assert served == ["10.0.0.1:8000"]


def test_grpc_service_name_and_method():
    from gie_tpu.extproc.pb.envoy.service.ext_proc.v3 import external_processor_pb2 as x

    svc = x.DESCRIPTOR.services_by_name["ExternalProcessor"]
    assert svc.full_name == "envoy.service.ext_proc.v3.ExternalProcessor"
    method = svc.methods_by_name["Process"]
    assert method.input_type.full_name == "envoy.service.ext_proc.v3.ProcessingRequest"
    assert method.output_type.full_name == "envoy.service.ext_proc.v3.ProcessingResponse"


# Descriptor-level pin: every load-bearing field number, in one table, so a
# future proto edit that drifts from Envoy fails with a precise message.
EXPECTED_FIELDS = {
    "ProcessingRequest": {
        "request_headers": 2,
        "request_body": 3,
        "request_trailers": 4,
        "response_headers": 5,
        "response_body": 6,
        "response_trailers": 7,
        "metadata_context": 8,
    },
    "ProcessingResponse": {
        "request_headers": 1,
        "request_body": 2,
        "request_trailers": 3,
        "response_headers": 4,
        "response_body": 5,
        "response_trailers": 6,
        "immediate_response": 7,
        "dynamic_metadata": 8,
    },
    "HttpHeaders": {"headers": 1, "end_of_stream": 3},
    "HttpBody": {"body": 1, "end_of_stream": 2},
    "ImmediateResponse": {
        "status": 1,
        "headers": 2,
        "body": 3,
        "grpc_status": 4,
        "details": 5,
    },
    "CommonResponse": {
        "status": 1,
        "header_mutation": 2,
        "body_mutation": 3,
        "trailers": 4,
        "clear_route_cache": 5,
    },
    "HeaderValue": {"key": 1, "value": 2, "raw_value": 3},
    "HeaderMutation": {"set_headers": 1, "remove_headers": 2},
}


@pytest.mark.parametrize("message_name", sorted(EXPECTED_FIELDS))
def test_descriptor_field_numbers(message_name):
    msg = getattr(pb, message_name)
    actual = {f.name: f.number for f in msg.DESCRIPTOR.fields}
    for name, number in EXPECTED_FIELDS[message_name].items():
        assert actual.get(name) == number, (
            f"{message_name}.{name} is {actual.get(name)}, Envoy wire = {number}"
        )


def test_message_full_names_are_envoy():
    assert pb.ProcessingRequest.DESCRIPTOR.full_name == (
        "envoy.service.ext_proc.v3.ProcessingRequest"
    )
    assert pb.HeaderValue.DESCRIPTOR.full_name == "envoy.config.core.v3.HeaderValue"
    assert pb.HttpStatus.DESCRIPTOR.full_name == "envoy.type.v3.HttpStatus"
    assert (
        pb.Metadata.DESCRIPTOR.full_name == "envoy.config.core.v3.Metadata"
    )
