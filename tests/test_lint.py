"""gie-lint meta-suite (ISSUE 6): the analyzers themselves are pinned —
each rule fires on its golden-violation fixture and stays silent on the
matching negative, the baseline machinery enforces its justification /
no-stale-entries contract, and ``gie_tpu/`` at HEAD is CLEAN modulo the
baseline (the tier-1 guarantee behind ``make lint``)."""

from __future__ import annotations

import os
import subprocess
import sys

import pytest

from gie_tpu.lint import baseline, tomlmini
from gie_tpu.lint.model import Violation
from gie_tpu.lint.runner import DEFAULT_BASELINE, run_paths

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures", "lint")
FIXTURE_CONFIG = os.path.join(FIXTURES, "lockorder.toml")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_fixture(stem: str, rule: str) -> list[Violation]:
    """Analyze one fixture file, filtered to one rule family (fixtures
    share a config, so other files' GL004 stale-rank noise is
    expected and must be filtered, not asserted on)."""
    violations, stale = run_paths(
        [os.path.join(FIXTURES, f"{stem}.py")],
        config=FIXTURE_CONFIG,
        baseline_path="",
        rules={rule},
    )
    assert stale == []
    return violations


# --------------------------------------------------------------------------
# Golden violations: one positive + one negative per rule
# --------------------------------------------------------------------------


@pytest.mark.parametrize(
    "stem,rule,expected_substrings",
    [
        ("gl001_bad", "GL001", [
            "acquires gl001_bad.Outer._outer (rank 10) while holding "
            "gl001_bad.Outer._inner (rank 20)",
            "while holding gl001_bad.Helper._lock (rank 30) via",
            "self-deadlock",
            # `with self._inner, self._outer:` — the in-statement pair.
            "inverted_one_statement",
        ]),
        ("gl002_bad", "GL002", [
            "time.sleep",
            "json.loads",
            "http.client.HTTPConnection.getresponse",
            "via gl002_bad.py:slow_helper",         # transitive chain
            "numpy.asarray (device sync)",          # D2H under lock
        ]),
        ("gl003_bad", "GL003", ["gl003_bad.Rogue._unranked"]),
        ("gl004_stale", "GL004", ["gl004_stale.Gone._lock"]),
        ("gt001_bad", "GT001", ["import time"]),
        ("gt002_bad", "GT002", [
            "float() on a traced value",
            "print() inside traced code",
            "numpy.asarray() inside traced code",
            "time.time() inside traced code",
            ".item() inside traced code",
            "called from jit via gt002_bad.py:score",   # reachability
        ]),
        ("gt003_bad", "GT003", ["block_until_ready"]),
        ("gt004_bad", "GT004", [
            "jax.device_get in the sharded-cycle layer",
            "block_until_ready in the sharded-cycle layer",
            ".item() in the sharded-cycle layer",
            ".tolist() in the sharded-cycle layer",
        ]),
        ("ga001_bad", "GA001", [
            "time.sleep",
            "urllib.request.urlopen inside async function via",
            "threading.Event.wait",
        ]),
        ("gr001_bad", "GR001", [
            "queue.Queue.get() inside a daemon loop",
            "threading.Event.wait() inside a daemon loop",
            "socket.socket.recv() inside a daemon loop",
            "gr001_bad.Loop._lock.acquire() inside a daemon loop",
        ]),
        ("gc001_bad", "GC001", [
            "time.monotonic() in a clock-governed module",
            "time.time() in a clock-governed module",
            "time.sleep() in a clock-governed module",
            # The module-level clock pin (import-time calls never enter
            # a FunctionInfo; the rule walks the module body too).
            "[<module>] direct time.monotonic()",
        ]),
    ],
)
def test_rule_fires_on_golden_fixture(stem, rule, expected_substrings):
    violations = run_fixture(stem, rule)
    assert violations, f"{rule} found nothing in {stem}.py"
    rendered = "\n".join(v.render() for v in violations)
    for sub in expected_substrings:
        assert sub in rendered, (
            f"{rule} on {stem}.py missing expected finding {sub!r}:\n"
            f"{rendered}")


def test_gt001_counts_every_import_time_shape():
    # Module level, backend query, class body, default arg: all four.
    assert len(run_fixture("gt001_bad", "GT001")) == 4


@pytest.mark.parametrize(
    "stem,rule",
    [
        ("gl001_ok", "GL001"),
        ("gl002_ok", "GL002"),
        ("gt001_ok", "GT001"),
        ("gt002_ok", "GT002"),
        ("gt004_ok", "GT004"),
        ("ga001_ok", "GA001"),
        ("gr001_ok", "GR001"),
        ("gc001_ok", "GC001"),
    ],
)
def test_rule_silent_on_negative_fixture(stem, rule):
    violations = run_fixture(stem, rule)
    assert violations == [], (
        f"{rule} false positives in {stem}.py:\n"
        + "\n".join(v.render() for v in violations))


def test_gt002_does_not_flag_host_side_code():
    # gt002_bad.plain uses print/float but is unreachable from jit.
    assert not any(
        v.qualname == "plain" for v in run_fixture("gt002_bad", "GT002"))


# --------------------------------------------------------------------------
# The repo itself is clean (the `make lint` gate)
# --------------------------------------------------------------------------


def test_gie_tpu_clean_modulo_baseline():
    violations, stale = run_paths()
    assert violations == [], (
        "gie_tpu/ has unbaselined lint findings — fix them or "
        "grandfather WITH justification in gie_tpu/lint/baseline.toml:\n"
        + "\n".join(v.render() for v in violations))
    assert stale == [], (
        "stale baseline entries (no longer matching any finding):\n"
        + "\n".join(f"{e.rule} at {e.where}" for e in stale))


def test_every_repo_lock_is_ranked():
    """The declared hierarchy covers every lock in gie_tpu/ — GL003
    firing on HEAD would already fail the clean test, but this pins the
    inverse too: the config names only locks that exist."""
    from gie_tpu.lint.model import RepoIndex

    idx = RepoIndex.build(
        os.path.join(REPO, "gie_tpu"), package_prefix="gie_tpu.")
    ranks = tomlmini.load(
        os.path.join(REPO, "gie_tpu", "lint", "lockorder.toml"))["ranks"]
    assert set(idx.locks) == set(ranks)


def test_cli_exit_codes():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    clean = subprocess.run(
        [sys.executable, "-m", "gie_tpu.lint"],
        cwd=REPO, capture_output=True, env=env)
    assert clean.returncode == 0, clean.stdout + clean.stderr
    dirty = subprocess.run(
        [sys.executable, "-m", "gie_tpu.lint",
         os.path.join(FIXTURES, "gl002_bad.py"),
         "--config", FIXTURE_CONFIG, "--no-baseline", "--rules", "GL002"],
        cwd=REPO, capture_output=True, env=env)
    assert dirty.returncode == 1, dirty.stdout + dirty.stderr


# --------------------------------------------------------------------------
# Baseline machinery
# --------------------------------------------------------------------------


def _violation(rule="GL002", where="f.py:C.m", msg="blocking call x"):
    file, qualname = where.rsplit(":", 1)
    return Violation(rule, file, 1, qualname, msg)


def test_baseline_requires_justification(tmp_path):
    p = tmp_path / "baseline.toml"
    p.write_text(
        '[[finding]]\nrule = "GL002"\nwhere = "f.py:C.m"\n'
        'match = "x"\njustification = "   "\n')
    with pytest.raises(baseline.BaselineError, match="justification"):
        baseline.load(str(p))


def test_baseline_covers_and_reports_stale(tmp_path):
    p = tmp_path / "baseline.toml"
    p.write_text(
        '[[finding]]\nrule = "GL002"\nwhere = "f.py:C.m"\n'
        'match = "blocking"\njustification = "legacy, tracked in #1"\n'
        '[[finding]]\nrule = "GL001"\nwhere = "gone.py:X.y"\n'
        'match = ""\njustification = "obsolete"\n')
    entries = baseline.load(str(p))
    remaining, stale = baseline.apply([_violation()], entries)
    assert remaining == []                      # covered finding hidden
    assert [e.where for e in stale] == ["gone.py:X.y"]   # stale caught


def test_baseline_does_not_cover_new_findings(tmp_path):
    p = tmp_path / "baseline.toml"
    p.write_text(
        '[[finding]]\nrule = "GL002"\nwhere = "f.py:C.m"\n'
        'match = "blocking"\njustification = "legacy"\n')
    new = _violation(where="other.py:D.n", msg="blocking call y")
    remaining, _ = baseline.apply([new], baseline.load(str(p)))
    assert remaining == [new]


def test_rules_filter_does_not_strand_baseline_entries(tmp_path):
    """--rules GL must not report a GT/GA baseline entry as stale: the
    restricted run never computed those findings, so it cannot judge
    their entries."""
    p = tmp_path / "baseline.toml"
    p.write_text(
        '[[finding]]\nrule = "GT003"\nwhere = "x.py:C.m"\n'
        'match = "block_until_ready"\njustification = "legacy bench"\n')
    _, stale = run_paths(
        [os.path.join(FIXTURES, "gl001_ok.py")],
        config=FIXTURE_CONFIG,
        baseline_path=str(p),
        rules={"GL"},
    )
    assert stale == []


def test_repo_baseline_is_loadable():
    entries = baseline.load(DEFAULT_BASELINE)
    for e in entries:
        assert e.justification  # load() enforces; double-pin the contract


# --------------------------------------------------------------------------
# tomlmini: the config reader the whole suite leans on
# --------------------------------------------------------------------------


def test_tomlmini_subset():
    doc = tomlmini.loads(
        '# comment\n'
        'top = "v"\n'
        '[ranks]\n'
        '"a.b.c" = 10\n'
        'plain = 2.5\n'
        'flag = true\n'
        '[blocking]\n'
        'calls = [\n    "time.sleep",  # trailing comment\n'
        '    "json.loads",\n]\n'
        '[[finding]]\nrule = "GL001"\n'
        '[[finding]]\nrule = "GL002"\n')
    assert doc["top"] == "v"
    assert doc["ranks"]["a.b.c"] == 10
    assert doc["ranks"]["plain"] == 2.5
    assert doc["ranks"]["flag"] is True
    assert doc["blocking"]["calls"] == ["time.sleep", "json.loads"]
    assert [f["rule"] for f in doc["finding"]] == ["GL001", "GL002"]


def test_tomlmini_rejects_garbage():
    with pytest.raises(ValueError):
        tomlmini.loads("not a toml line\n")
    with pytest.raises(ValueError):
        tomlmini.loads('x = [1, 2\n')   # unterminated array
