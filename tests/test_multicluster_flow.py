"""Multi-cluster export/import (proposal 1374 + apix/v1alpha1) and
flow-control fairness tests."""

from gie_tpu.api import types as api
from gie_tpu.controller.multicluster import CONTROLLER_NAME, ClusterSet
from gie_tpu.extproc import metadata as mdkeys
from gie_tpu.extproc.server import PickRequest
from gie_tpu.sched.batching import _fair_order, _Pending


def make_pool(name="pool", export=True):
    annotations = (
        {api.EXPORT_ANNOTATION: api.EXPORT_SCOPE_CLUSTERSET} if export else {}
    )
    return api.InferencePool(
        metadata=api.ObjectMeta(name=name, annotations=annotations),
        spec=api.InferencePoolSpec(
            selector=api.LabelSelector(matchLabels={"app": "vllm"}),
            targetPorts=[api.Port(8000)],
            endpointPickerRef=api.EndpointPickerRef(name="epp", port=api.Port(9002)),
        ),
    )


def test_export_materializes_imports_in_other_members():
    cs = ClusterSet(["east", "west", "south"])
    cs.apply_pool("east", make_pool())
    for member in ("west", "south"):
        imp = cs.get_import(member, "default", "pool")
        assert imp is not None
        ctrl = imp.status.controllers[0]
        assert ctrl.name == CONTROLLER_NAME
        assert [c.name for c in ctrl.exportingClusters] == ["east"]
    # Never in the exporting cluster itself.
    assert cs.get_import("east", "default", "pool") is None


def test_exported_condition_on_pool():
    cs = ClusterSet(["east", "west"])
    pool = make_pool()
    cs.apply_pool("east", pool)
    conds = [
        p.get_condition(api.COND_EXPORTED)
        for p in pool.status.parents
        if p.parentRef.kind == "InferencePoolImport"
    ]
    assert conds[0].status == "True" and conds[0].reason == api.REASON_EXPORTED

    unexported = make_pool(name="local", export=False)
    cs.apply_pool("east", unexported)
    conds = [
        p.get_condition(api.COND_EXPORTED)
        for p in unexported.status.parents
        if p.parentRef.kind == "InferencePoolImport"
    ]
    assert conds[0].status == "False"
    assert conds[0].reason == api.REASON_NOT_REQUESTED
    assert cs.get_import("west", "default", "local") is None


def test_multiple_exporters_merge_and_prune():
    cs = ClusterSet(["east", "west", "south"])
    cs.apply_pool("east", make_pool())
    cs.apply_pool("west", make_pool())
    imp = cs.get_import("south", "default", "pool")
    assert [c.name for c in imp.status.controllers[0].exportingClusters] == [
        "east", "west",
    ]
    cs.delete_pool("east", "default", "pool")
    imp = cs.get_import("south", "default", "pool")
    assert [c.name for c in imp.status.controllers[0].exportingClusters] == [
        "west",
    ]
    cs.delete_pool("west", "default", "pool")
    assert cs.get_import("south", "default", "pool") is None


def test_fair_order_interleaves_tenants():
    def pending(fid, i):
        p = _Pending(
            PickRequest(headers={mdkeys.FLOW_FAIRNESS_ID_KEY: [fid]},
                        body=b"%d" % i),
            candidates=[object()],
        )
        return p

    # Tenant A floods with 6 requests; B and C have 2 each.
    items = [pending("A", i) for i in range(6)]
    items += [pending("B", i) for i in range(2)]
    items += [pending("C", i) for i in range(2)]
    ordered = _fair_order(items)
    first_six = [it.req.headers[mdkeys.FLOW_FAIRNESS_ID_KEY][0]
                 for it in ordered[:6]]
    # Every tenant appears within the first wave of 6.
    assert set(first_six) == {"A", "B", "C"}
    # Per-tenant FIFO preserved.
    a_bodies = [it.req.body for it in ordered
                if it.req.headers[mdkeys.FLOW_FAIRNESS_ID_KEY][0] == "A"]
    assert a_bodies == sorted(a_bodies, key=lambda b: int(b))


def test_unsupported_export_scope_not_supported_reason():
    cs = ClusterSet(["east", "west"])
    pool = make_pool(export=False)
    pool.metadata.annotations[api.EXPORT_ANNOTATION] = "Region"
    cs.apply_pool("east", pool)
    conds = [
        p.get_condition(api.COND_EXPORTED)
        for p in pool.status.parents
        if p.parentRef.kind == "InferencePoolImport"
    ]
    assert conds[0].status == "False"
    assert conds[0].reason == api.REASON_NOT_SUPPORTED
    assert cs.get_import("west", "default", "pool") is None


def test_fair_order_criticality_bands_before_fairness():
    """CRITICAL drains before SHEDDABLE even when other tenants flood."""
    def pending(fid, obj, i):
        return _Pending(
            PickRequest(headers={
                mdkeys.FLOW_FAIRNESS_ID_KEY: [fid],
                mdkeys.OBJECTIVE_KEY: [obj],
            }, body=b"%d" % i),
            candidates=[object()],
        )

    items = [pending("B", "sheddable", i) for i in range(4)]
    items += [pending("C", "sheddable", i) for i in range(4)]
    items.append(pending("A", "critical", 0))  # arrived last
    ordered = _fair_order(items)
    assert ordered[0].req.headers[mdkeys.OBJECTIVE_KEY][0] == "critical"


# --------------------------------------------------------------------- #
# Routing-mode consumption (1374 README 'Routing Modes' + 'Data Path'):
# requests on an importing cluster's route referencing an
# InferencePoolImport land on an exporting cluster's endpoint.
# --------------------------------------------------------------------- #

from conformance.harness import ConformanceEnv  # noqa: E402
from conformance.multicluster import (  # noqa: E402
    MultiClusterInferenceEnv,
    ROUTING_MODE_ENDPOINT,
    ROUTING_MODE_PARENT,
)
from gie_tpu.api.gateway import (  # noqa: E402
    BackendRef,
    Gateway,
    HTTPRoute,
    RouteRule,
    Service,
    ROUTE_RESOLVED_REFS,
)


def harness_pool(name="pool", export=True):
    pool = make_pool(name=name, export=export)
    return pool


def _exporting_cluster(mc, cluster, pool_name="pool", pods=3,
                       with_gateway=False):
    env = mc.env(cluster)
    env.apply_service(Service(name="epp"))
    pods = env.deploy_model_servers(
        f"{cluster}-vllm", pods, labels={"app": "vllm"})
    mc.apply_pool(cluster, harness_pool(name=pool_name))
    if with_gateway:
        env.apply_gateway(Gateway(name=f"{cluster}-gw"))
        env.apply_route(HTTPRoute(
            name=f"{cluster}-route",
            parent_gateways=[f"{cluster}-gw"],
            rules=[RouteRule(backend_refs=[BackendRef(name=pool_name)])],
        ))
    return [p.name for p in pods]


def _importing_cluster(mc, cluster, import_name="pool"):
    env = mc.env(cluster)
    env.apply_gateway(Gateway(name=f"{cluster}-gw"))
    env.apply_route(HTTPRoute(
        name=f"{cluster}-route",
        parent_gateways=[f"{cluster}-gw"],
        rules=[RouteRule(backend_refs=[BackendRef(
            name=import_name,
            kind="InferencePoolImport",
            group=api.GROUP_X,
        )])],
    ))
    return env


def test_endpoint_mode_routes_to_exporting_cluster():
    mc = MultiClusterInferenceEnv(["east", "west"],
                                  routing_mode=ROUTING_MODE_ENDPOINT)
    try:
        east_pods = _exporting_cluster(mc, "east")
        west = _importing_cluster(mc, "west")
        # The importing route resolves the import.
        ps = west.routes[("default", "west-route")].parent_status("west-gw")
        assert ps.get_condition(ROUTE_RESOLVED_REFS).status == "True"
        for _ in range(6):
            resp = west.send("west-gw", "any.host", "/v1/completions",
                             body=b"hi")
            assert resp.status == 200
            assert resp.backend_pod in east_pods
    finally:
        mc.close()


def test_parent_mode_routes_via_remote_gateway():
    mc = MultiClusterInferenceEnv(["east", "west"],
                                  routing_mode=ROUTING_MODE_PARENT)
    try:
        east_pods = _exporting_cluster(mc, "east", with_gateway=True)
        west = _importing_cluster(mc, "west")
        resp = west.send("west-gw", "any.host", "/v1/completions", body=b"hi")
        assert resp.status == 200 and resp.backend_pod in east_pods
        # Parent mode REQUIRES a remote parent: removing the exporting
        # cluster's route must break the path (Endpoint mode would not).
        mc.env("east").delete_route("default", "east-route")
        resp = west.send("west-gw", "any.host", "/v1/completions", body=b"hi")
        assert resp.status == 503 and b"no remote parent gateway" in resp.body
    finally:
        mc.close()


def test_weighted_split_local_pool_and_import():
    """50/50 weighted backendRefs across a local InferencePool and an
    InferencePoolImport balance across clusters (1374 README example)."""
    mc = MultiClusterInferenceEnv(["east", "west"])
    try:
        east_pods = _exporting_cluster(mc, "east")
        west = mc.env("west")
        west.apply_service(Service(name="epp"))
        west_pods = [p.name for p in west.deploy_model_servers(
            "west-vllm", 3, labels={"app": "vllm"})]
        mc.apply_pool("west", harness_pool(name="local", export=False))
        west.apply_gateway(Gateway(name="west-gw"))
        west.apply_route(HTTPRoute(
            name="west-route",
            parent_gateways=["west-gw"],
            rules=[RouteRule(backend_refs=[
                BackendRef(name="local", weight=50),
                BackendRef(name="pool", kind="InferencePoolImport",
                           group=api.GROUP_X, weight=50),
            ])],
        ))
        served = {"east": 0, "west": 0}
        for _ in range(60):
            resp = west.send("west-gw", "any.host", "/", body=b"x")
            assert resp.status == 200
            served["east" if resp.backend_pod in east_pods else "west"] += 1
            assert resp.backend_pod in east_pods + west_pods
        assert served["east"] >= 10 and served["west"] >= 10
    finally:
        mc.close()


def test_active_passive_exporter_failover():
    """Two exporters: EPP readiness picks the active one (1374 README
    'InferencePool Selection', Active-Passive)."""
    mc = MultiClusterInferenceEnv(["east", "south", "west"])
    try:
        east_pods = _exporting_cluster(mc, "east")
        south_pods = _exporting_cluster(mc, "south")
        west = _importing_cluster(mc, "west")
        resp = west.send("west-gw", "h", "/", body=b"x")
        assert resp.backend_pod in east_pods  # first in ClusterSet order
        mc.env("east").scale_epp("default", "pool", 0)
        resp = west.send("west-gw", "h", "/", body=b"x")
        assert resp.backend_pod in south_pods  # failed over
        mc.env("east").scale_epp("default", "pool", 1)
        resp = west.send("west-gw", "h", "/", body=b"x")
        assert resp.backend_pod in east_pods  # failed back
    finally:
        mc.close()


def test_export_withdrawn_prunes_import_and_unresolves_route():
    mc = MultiClusterInferenceEnv(["east", "west"])
    try:
        _exporting_cluster(mc, "east")
        west = _importing_cluster(mc, "west")
        assert west.imports  # materialized
        # Withdraw the export (annotation removed -> reconcile).
        unexported = harness_pool(export=False)
        mc.apply_pool("east", unexported)
        assert not west.imports
        ps = west.routes[("default", "west-route")].parent_status("west-gw")
        cond = ps.get_condition(ROUTE_RESOLVED_REFS)
        assert cond.status == "False"
        resp = west.send("west-gw", "h", "/", body=b"x")
        assert resp.status == 500
    finally:
        mc.close()


def test_import_controller_parent_status_maintained():
    """The importing controller records the local Gateway in the import's
    status.controllers[].parents, and removes it when the route goes away
    (1374 README 'Import Controller' responsibilities)."""
    from conformance.harness import GATEWAY_CONTROLLER_NAME

    mc = MultiClusterInferenceEnv(["east", "west"])
    try:
        _exporting_cluster(mc, "east")
        west = _importing_cluster(mc, "west")
        imp = west.imports[("default", "pool")]
        gw_entries = [c for c in imp.status.controllers
                      if c.name == GATEWAY_CONTROLLER_NAME]
        assert len(gw_entries) == 1
        parent = gw_entries[0].parents[0]
        assert parent.parentRef.name == "west-gw"
        assert parent.parentRef.kind == "Gateway"
        assert parent.get_condition(api.COND_ACCEPTED).status == "True"
        # Export-controller entry still present alongside.
        assert any(c.name == CONTROLLER_NAME
                   for c in imp.status.controllers)
        west.delete_route("default", "west-route")
        imp = west.imports[("default", "pool")]
        assert not [c for c in imp.status.controllers
                    if c.name == GATEWAY_CONTROLLER_NAME]
    finally:
        mc.close()


def test_mutual_import_loop_terminates():
    """Two clusters whose routes weighted-split into each other's imports
    must terminate with a response (possibly 508), never recurse without
    bound (Parent mode re-enters send() on the remote cluster)."""
    mc = MultiClusterInferenceEnv(["east", "west"],
                                  routing_mode=ROUTING_MODE_PARENT)
    try:
        for c in ("east", "west"):
            env = mc.env(c)
            env.apply_service(Service(name="epp"))
            env.deploy_model_servers(f"{c}-vllm", 2, labels={"app": "vllm"})
            mc.apply_pool(c, harness_pool())
        for c in ("east", "west"):
            env = mc.env(c)
            env.apply_gateway(Gateway(name=f"{c}-gw"))
            env.apply_route(HTTPRoute(
                name=f"{c}-route", parent_gateways=[f"{c}-gw"],
                rules=[RouteRule(backend_refs=[
                    # The 0-weighted local pool ref makes this route a
                    # discoverable parent of the pool, but every pick goes
                    # to the import of the OTHER cluster's pool: a pure
                    # cross-cluster ping-pong.
                    BackendRef(name="pool", weight=0),
                    BackendRef(name="pool", kind="InferencePoolImport",
                               group=api.GROUP_X, weight=1),
                ])],
            ))
        resp = mc.env("west").send("west-gw", "h", "/", body=b"x")
        assert resp.status == 508
    finally:
        mc.close()
