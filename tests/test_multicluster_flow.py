"""Multi-cluster export/import (proposal 1374 + apix/v1alpha1) and
flow-control fairness tests."""

from gie_tpu.api import types as api
from gie_tpu.controller.multicluster import CONTROLLER_NAME, ClusterSet
from gie_tpu.extproc import metadata as mdkeys
from gie_tpu.extproc.server import PickRequest
from gie_tpu.sched.batching import _fair_order, _Pending


def make_pool(name="pool", export=True):
    annotations = (
        {api.EXPORT_ANNOTATION: api.EXPORT_SCOPE_CLUSTERSET} if export else {}
    )
    return api.InferencePool(
        metadata=api.ObjectMeta(name=name, annotations=annotations),
        spec=api.InferencePoolSpec(
            selector=api.LabelSelector(matchLabels={"app": "vllm"}),
            targetPorts=[api.Port(8000)],
            endpointPickerRef=api.EndpointPickerRef(name="epp", port=api.Port(9002)),
        ),
    )


def test_export_materializes_imports_in_other_members():
    cs = ClusterSet(["east", "west", "south"])
    cs.apply_pool("east", make_pool())
    for member in ("west", "south"):
        imp = cs.get_import(member, "default", "pool")
        assert imp is not None
        ctrl = imp.status.controllers[0]
        assert ctrl.name == CONTROLLER_NAME
        assert [c.name for c in ctrl.exportingClusters] == ["east"]
    # Never in the exporting cluster itself.
    assert cs.get_import("east", "default", "pool") is None


def test_exported_condition_on_pool():
    cs = ClusterSet(["east", "west"])
    pool = make_pool()
    cs.apply_pool("east", pool)
    conds = [
        p.get_condition(api.COND_EXPORTED)
        for p in pool.status.parents
        if p.parentRef.name == CONTROLLER_NAME
    ]
    assert conds[0].status == "True" and conds[0].reason == api.REASON_EXPORTED

    unexported = make_pool(name="local", export=False)
    cs.apply_pool("east", unexported)
    conds = [
        p.get_condition(api.COND_EXPORTED)
        for p in unexported.status.parents
        if p.parentRef.name == CONTROLLER_NAME
    ]
    assert conds[0].status == "False"
    assert conds[0].reason == api.REASON_NOT_REQUESTED
    assert cs.get_import("west", "default", "local") is None


def test_multiple_exporters_merge_and_prune():
    cs = ClusterSet(["east", "west", "south"])
    cs.apply_pool("east", make_pool())
    cs.apply_pool("west", make_pool())
    imp = cs.get_import("south", "default", "pool")
    assert [c.name for c in imp.status.controllers[0].exportingClusters] == [
        "east", "west",
    ]
    cs.delete_pool("east", "default", "pool")
    imp = cs.get_import("south", "default", "pool")
    assert [c.name for c in imp.status.controllers[0].exportingClusters] == [
        "west",
    ]
    cs.delete_pool("west", "default", "pool")
    assert cs.get_import("south", "default", "pool") is None


def test_fair_order_interleaves_tenants():
    def pending(fid, i):
        p = _Pending(
            PickRequest(headers={mdkeys.FLOW_FAIRNESS_ID_KEY: [fid]},
                        body=b"%d" % i),
            candidates=[object()],
        )
        return p

    # Tenant A floods with 6 requests; B and C have 2 each.
    items = [pending("A", i) for i in range(6)]
    items += [pending("B", i) for i in range(2)]
    items += [pending("C", i) for i in range(2)]
    ordered = _fair_order(items)
    first_six = [it.req.headers[mdkeys.FLOW_FAIRNESS_ID_KEY][0]
                 for it in ordered[:6]]
    # Every tenant appears within the first wave of 6.
    assert set(first_six) == {"A", "B", "C"}
    # Per-tenant FIFO preserved.
    a_bodies = [it.req.body for it in ordered
                if it.req.headers[mdkeys.FLOW_FAIRNESS_ID_KEY][0] == "A"]
    assert a_bodies == sorted(a_bodies, key=lambda b: int(b))


def test_unsupported_export_scope_not_supported_reason():
    cs = ClusterSet(["east", "west"])
    pool = make_pool(export=False)
    pool.metadata.annotations[api.EXPORT_ANNOTATION] = "Region"
    cs.apply_pool("east", pool)
    conds = [
        p.get_condition(api.COND_EXPORTED)
        for p in pool.status.parents
        if p.parentRef.name == CONTROLLER_NAME
    ]
    assert conds[0].status == "False"
    assert conds[0].reason == api.REASON_NOT_SUPPORTED
    assert cs.get_import("west", "default", "pool") is None


def test_fair_order_criticality_bands_before_fairness():
    """CRITICAL drains before SHEDDABLE even when other tenants flood."""
    def pending(fid, obj, i):
        return _Pending(
            PickRequest(headers={
                mdkeys.FLOW_FAIRNESS_ID_KEY: [fid],
                mdkeys.OBJECTIVE_KEY: [obj],
            }, body=b"%d" % i),
            candidates=[object()],
        )

    items = [pending("B", "sheddable", i) for i in range(4)]
    items += [pending("C", "sheddable", i) for i in range(4)]
    items.append(pending("A", "critical", 0))  # arrived last
    ordered = _fair_order(items)
    assert ordered[0].req.headers[mdkeys.OBJECTIVE_KEY][0] == "critical"
