"""HA state replication: codec robustness, digest round-trips, sync loop.

Three tiers of coverage, all tier-1 safe (single process, loopback only):

  codec     fuzz/robustness — truncated frames, flipped bytes, unknown
            future sections, bogus versions/flags, random junk: everything
            malformed returns None, NOTHING raises into the follower loop
            (extends the tests/test_protocol_fuzz.py posture to the
            replication wire format).
  state     export/install round-trips must be BIT-exact for the prefix
            table, assumed load, sinkhorn duals, predictor params, and the
            capacity EWMA — and installs must reject cross-field shape
            corruption the same way profile.py's checkpoint restore does.
  sync      publisher->follower smoke over the in-memory transport and the
            real HTTP listener: full snapshot, ETag 304, delta frames,
            epoch regression, era change (leader failover) forcing a full
            resync, and the manager's promote/demote wiring.

The two-process failover scenario lives in test_replication_failover.py
(marked slow; bounded <30s).
"""

import random

import numpy as np
import pytest

from gie_tpu.replication import (
    FollowerSync,
    ReplicationHTTPServer,
    ReplicationManager,
    StatePublisher,
    advertise_from_identity,
    codec,
    replication_identity,
)
from gie_tpu.replication import follower as fol_mod
from gie_tpu.sched.profile import ProfileConfig, Scheduler
from gie_tpu.utils.testing import make_endpoints, make_requests

# ---------------------------------------------------------------------------
# codec


def _sections(rng: np.random.Generator) -> dict:
    return {
        "sched": {
            "keys": rng.integers(0, 2**32, 64, dtype=np.uint32),
            "load": rng.standard_normal(16).astype(np.float32),
            "flag": np.bool_(True),
            "scalar": np.float32(np.nan),
        },
        "extra": {
            "i64": rng.integers(-5, 5, (3, 4), dtype=np.int64),
            "empty": np.zeros((0,), np.float64),
        },
    }


def test_codec_roundtrip_bit_exact(rng):
    sections = _sections(rng)
    blob = codec.encode_digest(42, sections)
    d = codec.decode_digest(blob)
    assert d is not None and d.epoch == 42 and not d.delta
    for name, arrays in sections.items():
        for key, arr in arrays.items():
            got = d.sections[name][key]
            assert got.dtype == np.asarray(arr).dtype
            assert np.array_equal(got, np.asarray(arr), equal_nan=True)


def test_codec_delta_header_roundtrip(rng):
    blob = codec.encode_digest(
        9, {"only": {"x": np.arange(3)}}, delta=True, base_epoch=7)
    d = codec.decode_digest(blob)
    assert d is not None and d.delta and d.base_epoch == 7 and d.epoch == 9


def test_codec_rejects_truncation_at_every_boundary(rng):
    blob = codec.encode_digest(3, _sections(rng))
    assert codec.decode_digest(blob) is not None
    # Every strict prefix must reject cleanly (sweep a stride plus the
    # interesting first/last few bytes).
    cuts = set(range(0, len(blob), 17)) | set(range(12)) | {
        len(blob) - k for k in range(1, 6)}
    for cut in sorted(cuts):
        assert codec.decode_digest(blob[:cut]) is None, f"cut={cut}"
    # Trailing junk is corruption too, not an extension point.
    assert codec.decode_digest(blob + b"\x00") is None


def test_codec_rejects_every_single_byte_flip(rng):
    """The CRC net has no holes: the header CRC covers epoch/flags/counts,
    each section CRC covers its name AND payload, and length-field flips
    shift the parse onto bytes whose CRC cannot match. EVERY single-byte
    corruption of a valid digest must reject whole."""
    blob = codec.encode_digest(3, _sections(rng))
    assert codec.decode_digest(blob) is not None
    for pos in range(len(blob)):
        mutated = bytearray(blob)
        mutated[pos] ^= 0x5A
        assert codec.decode_digest(bytes(mutated)) is None, f"pos={pos}"


def test_codec_rejects_random_junk():
    rng = random.Random(0)
    for _ in range(200):
        blob = rng.randbytes(rng.randint(0, 400))
        assert codec.decode_digest(blob) is None
    # Junk wearing the right magic must still reject.
    for _ in range(100):
        blob = codec.MAGIC + rng.randbytes(rng.randint(0, 200))
        assert codec.decode_digest(blob) is None


def test_codec_rejects_unknown_version_and_flags(rng):
    blob = bytearray(codec.encode_digest(1, {"s": {"x": np.arange(2)}}))
    v2 = bytearray(blob)
    v2[4] = codec.VERSION + 1  # version u16 LE low byte
    assert codec.decode_digest(bytes(v2)) is None
    f2 = bytearray(blob)
    f2[6] |= 0x80  # unknown flag bit
    assert codec.decode_digest(bytes(f2)) is None


def test_codec_unknown_future_section_decodes_and_installs_skip(rng):
    """Forward compat: a newer leader's extra section decodes fine and the
    manager's installer ignores it rather than failing the digest."""
    sched = Scheduler(ProfileConfig())
    blob = codec.encode_digest(1, {
        "sched": sched.export_state(),
        "from_the_future": {"mystery": rng.standard_normal(7)},
    })
    d = codec.decode_digest(blob)
    assert d is not None and "from_the_future" in d.sections
    mgr = ReplicationManager(scheduler=sched, port=0)
    try:
        assert mgr._install(d.sections, delta=False)
    finally:
        mgr.stop()


# ---------------------------------------------------------------------------
# state surfaces


def _warm_scheduler(m_slots: int = 64) -> Scheduler:
    sched = Scheduler(ProfileConfig())
    eps = make_endpoints(8, queue=[2.0] * 8, kv=[0.2] * 8, m_slots=m_slots)
    prompts = [b"SYS %d " % (i % 3) * 8 + b"user %d" % i for i in range(8)]
    reqs = make_requests(8, prompts=prompts, m_slots=m_slots)
    sched.pick(reqs, eps)
    return sched


def test_scheduler_digest_roundtrip_bit_exact():
    a = _warm_scheduler()
    exported = a.export_state()
    # Through the full codec, not just the dicts.
    d = codec.decode_digest(codec.encode_digest(1, {"sched": exported}))
    b = Scheduler(ProfileConfig())
    assert b.install_state(d.sections["sched"])
    again = b.export_state()
    for key, arr in exported.items():
        assert np.array_equal(arr, again[key]), key
    assert b.state.m == a.state.m


def test_scheduler_install_rejects_cross_field_corruption():
    a = _warm_scheduler()
    good = a.export_state()
    b = Scheduler(ProfileConfig())
    assert b.install_state(good)
    before = b.export_state()
    corruptions = [
        {"ot_v": good["ot_v"][:5]},                      # wrong dual width
        {"assumed_load": np.zeros((63,), np.float32)},   # not an M bucket
        {"prefix_present": good["prefix_present"][:100]},  # row mismatch
        {"prefix_ages": good["prefix_ages"][:-1]},       # ages != keys
        {"rr": np.zeros((4,), np.uint32)},               # non-scalar counter
    ]
    for patch in corruptions:
        bad = {**good, **patch}
        assert not b.install_state(bad), patch
    for key in good:
        missing = {k: v for k, v in good.items() if k != key}
        assert not b.install_state(missing), f"missing {key}"
    # Prior state survived every rejection (the follower's invariant).
    after = b.export_state()
    for key, arr in before.items():
        assert np.array_equal(arr, after[key]), key


def test_trainer_digest_roundtrip_and_rejects():
    from gie_tpu.models.latency import LatencyPredictor, OnlineTrainer

    a = OnlineTrainer(LatencyPredictor(), seed=1)
    a._loss_ema = 0.03
    a._observed_total = 500
    exported = a.export_state()
    b = OnlineTrainer(LatencyPredictor(), seed=2)
    assert b.install_state(exported)
    again = b.export_state()
    for key, arr in exported.items():
        assert np.array_equal(
            np.asarray(arr), np.asarray(again[key]), equal_nan=True), key
    assert b.confidence() == pytest.approx(a.confidence())
    # A differently-shaped param leaf (other architecture) rejects whole.
    some_param = next(k for k in exported if k.startswith("param"))
    bad = dict(exported)
    bad[some_param] = np.zeros(
        tuple(s + 1 for s in np.asarray(exported[some_param]).shape),
        np.float32)
    assert not b.install_state(bad)
    assert not b.install_state(
        {k: v for k, v in exported.items() if k != some_param})


def test_capacity_ewma_digest_and_checkpoint(tmp_path):
    from gie_tpu.autoscale.model import CapacityModel

    a = CapacityModel()
    a._ewma = 6.25
    b = CapacityModel()
    assert b.install_state(a.export_state())
    assert b.converged and b.per_replica() == pytest.approx(6.25)
    # Unconverged exports NaN and installs as "no estimate", not zero.
    c = CapacityModel()
    assert b.install_state(c.export_state()) is True
    assert not b.converged
    # utils/checkpoint persistence (leader shutdown -> restarted seed).
    a.save(str(tmp_path / "cap"))
    d = CapacityModel()
    assert d.restore(str(tmp_path / "cap"))
    assert d.converged and d.per_replica() == pytest.approx(6.25)
    assert not CapacityModel().restore(str(tmp_path / "nope"))
    assert not b.install_state({"wrong": np.float32(1.0)})


# ---------------------------------------------------------------------------
# publisher / follower protocol


class _MemFetch:
    """In-memory transport: follower wired straight to publisher.serve."""

    def __init__(self, publisher, leader=lambda: True):
        self.publisher = publisher
        self.leader = leader

    def __call__(self, base_url, since, era, etag):
        return self.publisher.serve(
            since=since, era=era, if_none_match=etag, leader=self.leader())


def test_publisher_epoch_bumps_only_on_change():
    state = {"x": np.arange(4, dtype=np.float32)}
    pub = StatePublisher({"s": lambda: dict(state)})
    assert pub.refresh() == 1
    assert pub.refresh() == 1
    state["x"] = state["x"] + 1.0
    assert pub.refresh() == 2
    assert pub.digest_bytes > 0


def test_publisher_delta_carries_only_changed_sections():
    s1 = {"x": np.arange(4, dtype=np.float32)}
    s2 = {"y": np.arange(8, dtype=np.float32)}
    pub = StatePublisher({"a": lambda: dict(s1), "b": lambda: dict(s2)})
    pub.refresh()                       # epoch 1: both sections
    s2["y"] = s2["y"] * 2.0
    assert pub.refresh() == 2           # only "b" changed
    status, headers, body = pub.serve(since=1, era=pub.era)
    assert status == 200
    d = codec.decode_digest(body)
    assert d.delta and d.base_epoch == 1 and set(d.sections) == {"b"}
    # Wrong era cannot get a delta: full snapshot fallback.
    _, _, full = pub.serve(since=1, era="someone-else")
    df = codec.decode_digest(full)
    assert not df.delta and set(df.sections) == {"a", "b"}


def test_publisher_304_and_not_leader_and_empty():
    pub = StatePublisher({"s": lambda: {"x": np.zeros(1)}})
    status, _, _ = pub.serve()
    assert status == 503                # nothing published yet
    pub.refresh()
    status, headers, _ = pub.serve()
    assert status == 200
    status, _, _ = pub.serve(if_none_match=headers["ETag"])
    assert status == 304
    status, _, _ = pub.serve(leader=False)
    assert status == 503                # followers never serve digests


def _install_into(target: dict):
    def install(sections, *, delta):
        target.update(sections)
        return True
    return install


def test_follower_full_delta_regression_and_era_change():
    state = {"x": np.arange(4, dtype=np.float32)}
    pub = StatePublisher({"s": lambda: dict(state)}, era="era-A")
    pub.refresh()
    got: dict = {}
    fol = FollowerSync(
        lambda: "mem://", _install_into(got),
        interval_s=0.0, fetch=_MemFetch(pub))
    assert fol.poll_once() == fol_mod.INSTALLED
    assert fol.installed_epoch == 1 and fol.installed_era == "era-A"
    assert fol.poll_once() == fol_mod.NOT_MODIFIED
    # Delta path: state changes -> the follower's next poll asks
    # ?since=1 and installs the delta against its installed base.
    state["x"] = state["x"] + 1.0
    pub.refresh()
    assert fol.poll_once() == fol_mod.INSTALLED
    assert fol.installed_epoch == 2
    assert fol.last_delta, "second install should ride the delta path"
    assert np.array_equal(got["s"]["x"], state["x"])
    # Epoch regression within one era: a replayed response must not move
    # state backward.
    old_status, old_headers, old_body = pub.serve()
    fol2 = FollowerSync(
        lambda: "mem://", _install_into({}), interval_s=0.0,
        fetch=lambda *a: (old_status, old_headers, old_body))
    fol2.installed_era = "era-A"
    fol2.installed_epoch = 5
    fol2._want_full = False
    assert fol2.poll_once() == fol_mod.STALE_EPOCH
    assert fol2.installed_epoch == 5
    # Era change (new leader incarnation): epoch 1 of era-B must INSTALL
    # even though 1 < 5 — epochs are only comparable within an era.
    pub_b = StatePublisher({"s": lambda: {"x": np.ones(2)}}, era="era-B")
    pub_b.refresh()
    fol2._fetch = _MemFetch(pub_b)
    fol2._next_poll = 0.0
    assert fol2.poll_once() == fol_mod.INSTALLED
    assert fol2.installed_era == "era-B" and fol2.installed_epoch == 1


def test_follower_delta_against_unknown_base_refetches_full():
    """A delta whose base is not the follower's installed epoch (stale
    cache / raced response) must NOT install — it forces a full-snapshot
    re-fetch on the immediate next poll."""
    state = {"x": np.arange(4, dtype=np.float32)}
    pub = StatePublisher({"s": lambda: dict(state)}, era="era-A")
    pub.refresh()
    # A canned delta frame claiming base epoch 5 (the follower is at 1).
    rogue = codec.encode_digest(
        6, {"s": {"x": np.zeros(4, np.float32)}}, delta=True, base_epoch=5)
    mem = _MemFetch(pub)
    mode = {"rogue": False}

    def fetch(base_url, since, era, etag):
        if mode["rogue"]:
            status, headers, _ = pub.serve(since=since, era=era)
            return status, headers, rogue
        return mem(base_url, since, era, etag)

    got: dict = {}
    fol = FollowerSync(
        lambda: "mem://", _install_into(got), interval_s=0.0, fetch=fetch)
    assert fol.poll_once() == fol_mod.INSTALLED
    assert fol.installed_epoch == 1
    mode["rogue"] = True
    assert fol.poll_once() == fol_mod.DELTA_MISMATCH
    assert fol.installed_epoch == 1     # nothing installed
    mode["rogue"] = False
    state["x"] = state["x"] + 5.0
    pub.refresh()
    out = fol.poll_once()
    assert out == fol_mod.INSTALLED and not fol.last_delta, (
        "recovery fetch must be a full snapshot")
    assert fol.installed_epoch == pub.epoch
    assert np.array_equal(got["s"]["x"], state["x"])


def test_follower_keeps_state_on_corrupt_and_rejected():
    pub = StatePublisher({"s": lambda: {"x": np.arange(3)}})
    pub.refresh()
    good, headers, body = pub.serve()
    corrupt = body[: len(body) // 2]
    fol = FollowerSync(
        lambda: "mem://", _install_into({}), interval_s=0.0,
        fetch=lambda *a: (200, headers, corrupt))
    assert fol.poll_once() == fol_mod.CORRUPT
    assert fol.installed_epoch == 0 and fol.rejects == 1
    # An installer rejection (validation failure) also keeps prior state.
    fol3 = FollowerSync(
        lambda: "mem://", lambda sections, *, delta: False,
        interval_s=0.0, fetch=_MemFetch(pub))
    assert fol3.poll_once() == fol_mod.REJECTED
    assert fol3.installed_epoch == 0
    # And an installer that RAISES is contained, never propagated.
    def boom(sections, *, delta):
        raise RuntimeError("installer bug")
    fol4 = FollowerSync(
        lambda: "mem://", boom, interval_s=0.0, fetch=_MemFetch(pub))
    assert fol4.poll_once() == fol_mod.REJECTED


def test_follower_backoff_on_no_leader_and_fetch_error():
    fol = FollowerSync(
        lambda: None, _install_into({}), interval_s=0.1, backoff_max_s=1.0)
    assert fol.poll_once(now=100.0) == fol_mod.NO_LEADER
    assert fol.poll_once(now=100.05) is None  # backoff window
    def dead_fetch(*a):
        raise OSError("connection refused")
    # jitter=0 makes the schedule deterministic: doubling from the poll
    # interval, capped at backoff_max (the jittered spread is a scalar on
    # top of exactly this sequence).
    fol2 = FollowerSync(
        lambda: "http://127.0.0.1:1", _install_into({}),
        interval_s=0.1, backoff_max_s=1.0, jitter=0.0, fetch=dead_fetch)
    t = 100.0
    delays = []
    for _ in range(5):
        assert fol2.poll_once(now=t) == fol_mod.FETCH_ERROR
        delays.append(round(fol2._next_poll - t, 6))
        t = fol2._next_poll
    assert delays == [0.2, 0.4, 0.8, 1.0, 1.0]


# ---------------------------------------------------------------------------
# HTTP transport + manager wiring (tier-1 smoke)


def test_http_round_trip_smoke():
    """Single-process publisher -> real HTTP listener -> follower install
    into a second scheduler: the tier-1 guard that replication correctness
    is exercised in every run (full snapshot AND 304 path)."""
    a = _warm_scheduler()
    pub = StatePublisher({"sched": a.export_state})
    pub.refresh()
    srv = ReplicationHTTPServer(pub, 0)
    try:
        b = Scheduler(ProfileConfig())
        fol = FollowerSync(
            lambda: f"http://127.0.0.1:{srv.port}",
            lambda sections, *, delta: b.install_state(sections["sched"]),
            interval_s=0.0)
        assert fol.poll_once() == fol_mod.INSTALLED
        assert fol.poll_once() == fol_mod.NOT_MODIFIED
        exported, again = a.export_state(), b.export_state()
        for key, arr in exported.items():
            assert np.array_equal(arr, again[key]), key
    finally:
        srv.close()


def test_manager_in_memory_sync_and_promotion():
    from types import SimpleNamespace

    a = _warm_scheduler()
    mgr_a = ReplicationManager(scheduler=a, port=0, interval_s=0.0)
    b = Scheduler(ProfileConfig())
    leader_holder = replication_identity(mgr_a.advertise, base="stack-a")
    role = {"leader": False}
    elector_b = SimpleNamespace(
        is_leader=lambda: role["leader"],
        holder_identity=lambda: leader_holder,
        identity="stack-b|127.0.0.1:1",
    )
    mgr_b = ReplicationManager(
        scheduler=b, elector=elector_b, port=0, interval_s=0.0)
    try:
        assert mgr_a.is_leader()          # no elector = single leader
        assert mgr_a.step() == "published"
        assert not mgr_b.is_leader() and not mgr_b.healthy()
        assert mgr_b.step() == fol_mod.INSTALLED
        assert mgr_b.healthy()
        exported, again = a.export_state(), b.export_state()
        for key, arr in exported.items():
            assert np.array_equal(arr, again[key]), key
        # Promotion: the warm state is already live; the callback records
        # the epoch it promoted with and the role gauge flips.
        role["leader"] = True
        mgr_b.on_role_change(True)
        assert mgr_b.promoted_with_epoch == mgr_b.follower.installed_epoch > 0
        assert mgr_b.is_leader() and mgr_b.healthy()
        assert mgr_b.step() == "published"
        # Demotion flips back to syncing on the next tick.
        role["leader"] = False
        mgr_b.on_role_change(False)
        assert mgr_b.step() in (
            fol_mod.INSTALLED, fol_mod.NOT_MODIFIED, None)
    finally:
        mgr_a.stop()
        mgr_b.stop()


def test_mixed_digest_rejects_without_partial_install():
    """A digest whose 'predictor' section fails validation must leave the
    scheduler UNTOUCHED too — installs are all-or-nothing, or a promotion
    racing the next poll would serve a mixed-epoch state."""
    from gie_tpu.models.latency import LatencyPredictor, OnlineTrainer

    leader_sched = _warm_scheduler()
    follower_sched = Scheduler(ProfileConfig())
    trainer = OnlineTrainer(LatencyPredictor(), seed=3)
    before = follower_sched.export_state()
    mgr = ReplicationManager(
        scheduler=follower_sched, trainer=trainer, port=0)
    try:
        sections = {
            "sched": leader_sched.export_state(),          # valid
            "predictor": {"param/bogus": np.zeros(3)},     # rejects
        }
        assert mgr._install(sections, delta=False) is False
        after = follower_sched.export_state()
        for key, arr in before.items():
            assert np.array_equal(arr, after[key]), (
                f"partial install leaked into scheduler state: {key}")
        # And the valid-everything digest still installs both.
        sections["predictor"] = trainer.export_state()
        assert mgr._install(sections, delta=False) is True
        leader_exp = leader_sched.export_state()
        follower_exp = follower_sched.export_state()
        for key, arr in leader_exp.items():
            assert np.array_equal(arr, follower_exp[key]), key
    finally:
        mgr.stop()


def test_options_reject_wildcard_bind_without_advertise():
    from gie_tpu.runtime.options import Options

    opts = Options(pool_name="p", replication_port=9005,
                   replication_bind="0.0.0.0")
    with pytest.raises(ValueError, match="advertise"):
        opts.validate()
    opts.replication_advertise = "10.0.0.7:9005"
    opts.validate()  # explicit advertise makes the wildcard bind fine


def test_identity_advertise_round_trip():
    ident = replication_identity("10.0.0.7:9005")
    assert advertise_from_identity(ident) == "10.0.0.7:9005"
    assert advertise_from_identity("plain-pid-uuid") is None
    assert advertise_from_identity("") is None
    assert advertise_from_identity(None) is None
    assert advertise_from_identity("x|not-an-addr") is None


def test_runner_wires_replication(tmp_path):
    """--replication-port wires the manager, embeds the advertise address
    in the elector identity, and exposes replication health."""
    import socket

    from gie_tpu.runtime.options import Options
    from gie_tpu.runtime.runner import ExtProcServerRunner

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    opts = Options(
        pool_name="pool", leader_elect=True,
        leader_lease_path=str(tmp_path / "lease"),
        replication_port=port)
    opts.validate()
    runner = ExtProcServerRunner(opts, object())  # file elector path
    try:
        assert runner.replication is not None
        assert advertise_from_identity(runner.elector.identity) == (
            f"127.0.0.1:{port}")
        assert runner.elector.on_role_change == (
            runner.replication.on_role_change)
        # Leader with no peer: healthy by definition once leading.
        assert runner.replication.is_leader() is False  # not started
    finally:
        runner.replication.stop()
        runner.picker.close()
        runner.scraper.close()


# ---------------------------------------------------------------------------
# follower-side KV-event merge over digest installs (ROADMAP PR 3 follow-up)


def _has_presence_bit(sched: Scheduler, chunk_hash: int, ep_slot: int) -> bool:
    keys = np.asarray(sched.state.prefix.keys)
    present = np.asarray(sched.state.prefix.present)
    row = int(chunk_hash) & (keys.shape[0] - 1)
    if keys[row] != np.uint32(chunk_hash):
        return False
    return bool((present[row, ep_slot // 32] >> (ep_slot % 32)) & 1)


def test_install_preserves_local_kv_events_newer_than_digest():
    """A follower's locally observed KV-cache events (model servers push
    ground truth straight to every EPP) must survive a digest install:
    the install replays the journaled events over the incoming state
    instead of letting a snapshot exported BEFORE the events overwrite
    them."""
    leader = _warm_scheduler()
    digest = leader.export_state()
    follower = Scheduler(ProfileConfig())
    assert follower.install_state(digest)

    stored = np.asarray([0xA1B2C3D4, 0x00C0FFEE, 0x12345678], np.uint32)
    follower.apply_prefix_events(3, stored, np.asarray([], np.uint32))
    for h in stored:
        assert _has_presence_bit(follower, int(h), 3)

    # Next poll reinstalls the SAME leader snapshot (leader hasn't seen
    # these chunks): without the merge this wiped the local events.
    assert follower.install_state(digest)
    for h in stored:
        assert _has_presence_bit(follower, int(h), 3), hex(int(h))

    # Removal events merge too: endpoint 3 reports evicting one chunk.
    follower.apply_prefix_events(
        3, np.asarray([], np.uint32), stored[:1])
    assert follower.install_state(digest)
    assert not _has_presence_bit(follower, int(stored[0]), 3)
    assert _has_presence_bit(follower, int(stored[1]), 3)


def test_install_kv_merge_respects_ttl_and_eviction():
    """Journal hygiene: events older than the replay TTL age out (the
    digest stream is presumed to have caught up), and an evicted
    endpoint's journal entries are dropped (a dead pod's bits must not be
    resurrected onto a reused slot)."""
    leader = _warm_scheduler()
    digest = leader.export_state()

    # TTL aging: with the TTL forced to zero the journal never replays.
    f1 = Scheduler(ProfileConfig())
    assert f1.install_state(digest)
    f1._KV_REPLAY_TTL_S = 0.0
    f1.apply_prefix_events(
        2, np.asarray([0xDEADBEEF], np.uint32), np.asarray([], np.uint32))
    assert _has_presence_bit(f1, 0xDEADBEEF, 2)
    import time as _time

    _time.sleep(0.01)
    assert f1.install_state(digest)
    assert not _has_presence_bit(f1, 0xDEADBEEF, 2)

    # Eviction pruning: PodDelete between the event and the next install.
    f2 = Scheduler(ProfileConfig())
    assert f2.install_state(digest)
    f2.apply_prefix_events(
        5, np.asarray([0xBEEFCAFE], np.uint32), np.asarray([], np.uint32))
    f2.evict_endpoint(5)
    assert f2.install_state(digest)
    assert not _has_presence_bit(f2, 0xBEEFCAFE, 5)


# --------------------------------------------------------------------------
# Cross-version digest forward compat between PEERS (ISSUE 12 satellite):
# a newer build's digest — unknown sections, unknown arrays inside known
# sections — must install cleanly on an older follower (skip-unknown),
# while corrupted frames and era regressions reject whole.
# --------------------------------------------------------------------------


def test_follower_skips_unknown_sections_from_newer_peer():
    sched = _warm_scheduler()
    blob = codec.encode_digest(5, {
        "sched": sched.export_state(),
        "fed.meta": {"era": np.asarray([1, 2], np.uint64)},
        "totally.future": {"x": np.arange(8, dtype=np.float32)},
    })
    digest = codec.decode_digest(blob)
    assert digest is not None
    assert set(digest.sections) == {"sched", "fed.meta", "totally.future"}
    # The manager's installer routes known sections and SKIPS unknowns.
    from gie_tpu.replication.manager import ReplicationManager

    follower_sched = _warm_scheduler()
    mgr = ReplicationManager(scheduler=follower_sched, port=0)
    try:
        assert mgr._install(digest.sections, delta=False)
    finally:
        mgr.stop()


def test_peer_frames_fuzz_corruption_rejects_whole(seeded_rng=None):
    """Every byte-flip of a federation-shaped digest must decode to
    None (CRC guard) or decode to an identical-content frame — never a
    silently different install (the same every-byte property the PR-3
    codec pinned, re-asserted over the federation sections)."""
    from gie_tpu.federation import summary as fed_summary

    sections = {
        fed_summary.META_SECTION: fed_summary.encode_meta(
            (3, 77), False, "west"),
        fed_summary.LOAD_SECTION: fed_summary.encode_load(
            [("10.9.0.1:8000", 1.5, 0.25, False)], max_endpoints=4),
    }
    blob = codec.encode_digest(9, sections)
    baseline = codec.decode_digest(blob)
    assert baseline is not None
    rng = np.random.default_rng(11)
    for _ in range(256):
        i = int(rng.integers(len(blob)))
        flipped = bytearray(blob)
        flipped[i] ^= 1 << int(rng.integers(8))
        digest = codec.decode_digest(bytes(flipped))
        if digest is None:
            continue  # rejected whole: the contract
        meta = fed_summary.decode_meta(
            digest.sections.get(fed_summary.META_SECTION))
        if meta is None:
            continue  # malformed KNOWN section: the installer rejects
        # Anything that decodes as meta must carry an ordered era pair
        # — a flipped era would be caught by the CRC, so it is intact.
        assert meta.era == (3, 77)


def test_era_pair_ordering_is_total():
    """The split-brain convergence rule rests on tuple ordering: seq
    dominates, token breaks ties — total and deterministic."""
    assert (2, 0) > (1, 2**62)
    assert (1, 5) > (1, 4)
    eras = [(2, 1), (1, 9), (2, 0), (1, 2)]
    assert max(eras) == (2, 1)
    assert sorted(eras) == sorted(eras, key=lambda e: (e[0], e[1]))
