"""Runtime tier tests: options lifecycle, logging levels, TLS generation +
hot reload, health gating, runner wiring."""

import argparse
import io
import json
import os
import time

import grpc
import pytest

from gie_tpu.runtime.logging import Logger, set_verbosity
from gie_tpu.runtime.options import Options
from gie_tpu.runtime.tls import CertReloader, create_self_signed_cert


def make_opts(**kw):
    parser = argparse.ArgumentParser()
    Options.add_flags(parser)
    args = parser.parse_args([])
    opts = Options.from_args(args)
    for k, v in kw.items():
        setattr(opts, k, v)
    return opts


def test_options_defaults_match_reference():
    """reference options.go:25-27 defaults."""
    o = make_opts(pool_name="p")
    assert (o.grpc_port, o.grpc_health_port, o.metrics_port) == (9002, 9003, 9090)
    assert o.secure_serving
    o.validate()


def test_options_validation():
    with pytest.raises(ValueError, match="pool-name"):
        make_opts().validate()
    with pytest.raises(ValueError, match="grpc-port"):
        make_opts(pool_name="p", grpc_port=0).validate()
    with pytest.raises(ValueError, match="-v"):
        make_opts(pool_name="p", verbosity=9).validate()


def test_logger_levels_and_structure():
    buf = io.StringIO()
    log = Logger("test", stream=buf, component="x")
    set_verbosity(2)
    log.v(4).info("hidden debug")
    log.info("visible", key="val")
    set_verbosity(5)
    log.v(5).info("trace now visible")
    set_verbosity(2)
    lines = [json.loads(x) for x in buf.getvalue().splitlines()]
    assert [x["msg"] for x in lines] == ["visible", "trace now visible"]
    assert lines[0]["component"] == "x" and lines[0]["key"] == "val"
    assert lines[1]["level"] == "trace"


def test_self_signed_cert_valid():
    """reference tls.go:33-74."""
    pytest.importorskip("cryptography")
    cert_pem, key_pem = create_self_signed_cert()
    from cryptography import x509
    from cryptography.hazmat.primitives.serialization import load_pem_private_key

    cert = x509.load_pem_x509_certificate(cert_pem)
    key = load_pem_private_key(key_pem, None)
    assert key.key_size == 4096
    assert (cert.not_valid_after_utc - cert.not_valid_before_utc).days >= 3649
    # usable as grpc server creds
    grpc.ssl_server_credentials([(key_pem, cert_pem)])


def test_cert_reloader_hot_swap(tmp_path):
    """reference certs.go:35-103."""
    pytest.importorskip("cryptography")
    c1, k1 = create_self_signed_cert("first")
    cert_f, key_f = tmp_path / "tls.crt", tmp_path / "tls.key"
    cert_f.write_bytes(c1)
    key_f.write_bytes(k1)
    r = CertReloader(str(cert_f), str(key_f), poll_s=0.05)
    try:
        assert r.current() == (c1, k1)
        c2, k2 = create_self_signed_cert("second")
        # ensure mtime actually changes on coarse filesystems
        time.sleep(0.05)
        cert_f.write_bytes(c2)
        key_f.write_bytes(k2)
        os.utime(cert_f)
        deadline = time.time() + 5
        while time.time() < deadline and r.current() == (c1, k1):
            time.sleep(0.05)
        assert r.current() == (c2, k2)
    finally:
        r.close()


def test_health_gated_on_pool_sync():
    """reference runserver.go:132-157: NOT_SERVING until PoolHasSynced."""
    from gie_tpu.runtime.health import start_dedicated_health_server
    from gie_tpu.extproc.pb import health_pb2

    ready = {"v": False}
    server, port = start_dedicated_health_server(lambda: ready["v"], 0)
    try:
        channel = grpc.insecure_channel(f"127.0.0.1:{port}")
        check = channel.unary_unary(
            "/grpc.health.v1.Health/Check",
            request_serializer=health_pb2.HealthCheckRequest.SerializeToString,
            response_deserializer=health_pb2.HealthCheckResponse.FromString,
        )
        resp = check(health_pb2.HealthCheckRequest(service=""))
        assert resp.status == health_pb2.HealthCheckResponse.NOT_SERVING
        ready["v"] = True
        resp = check(health_pb2.HealthCheckRequest(service=""))
        assert resp.status == health_pb2.HealthCheckResponse.SERVING
        resp = check(health_pb2.HealthCheckRequest(service="bogus.Service"))
        assert resp.status == health_pb2.HealthCheckResponse.SERVICE_UNKNOWN
        channel.close()
    finally:
        server.stop(0)


def test_restored_confidence_applies_at_startup(tmp_path):
    """A restarted EPP with a converged predictor checkpoint must apply the
    gated latency weight at construction, not after the first train tick
    (which needs ~batch_size fresh observations — indefinitely long under
    low traffic)."""
    import numpy as np

    from gie_tpu.controller.cluster import FakeCluster
    from gie_tpu.models.latency import (
        NUM_FEATURES, LatencyPredictor, OnlineTrainer,
    )
    from gie_tpu.runtime.options import Options
    from gie_tpu.runtime.runner import ExtProcServerRunner
    from gie_tpu.sched.config import tuned_profile

    # Converge a trainer and checkpoint it (confidence state rides along).
    t1 = OnlineTrainer(LatencyPredictor(), batch_size=64,
                       confidence_min_samples=128)
    rng = np.random.default_rng(7)
    for _ in range(256):
        f = rng.uniform(0, 1, NUM_FEATURES).astype(np.float32)
        t1.observe(f, ttft_s=0.1 + 2.0 * f[3], tpot_s=0.02)
    for _ in range(30):
        t1.train(steps=5)
    assert t1.confidence() > 0.0
    ckpt = str(tmp_path / "predictor")
    t1.save(ckpt)

    # Scheduler-config ceiling: latency weight 2.0.
    cfg_yaml = tmp_path / "sched.yaml"
    cfg_yaml.write_text("weights:\n  latency: 2.0\n")
    opts = Options(pool_name="p", enable_predictor=True,
                   predictor_checkpoint_dir=ckpt,
                   scheduler_config=str(cfg_yaml))
    runner = ExtProcServerRunner(opts, FakeCluster())
    try:
        # Freshly-restarted runner: restored confidence gates the column
        # NOW. (The runner's trainer has its own confidence_min_samples,
        # so compare against ITS view of the restored state, not t1's.)
        live = float(runner.scheduler.weights.latency)
        assert live == pytest.approx(2.0 * runner.trainer.confidence(),
                                     rel=1e-5)
        assert live > 0.0

        # Without a checkpoint the column starts at zero (untrained).
        opts2 = Options(pool_name="p", enable_predictor=True,
                        scheduler_config=str(cfg_yaml))
        runner2 = ExtProcServerRunner(opts2, FakeCluster())
        try:
            assert float(runner2.scheduler.weights.latency) == 0.0
        finally:
            runner2.stop()
    finally:
        # Unstopped runners leak their ScrapeEngine shard threads, which
        # keep rewriting global gauges (gie_breaker_open_endpoints) for
        # the rest of the pytest process.
        runner.stop()


def test_predictor_without_ceiling_skips_cycle_column():
    """With --enable-predictor but no weights.latency ceiling, the trainer
    (and SLO admission) run but the jitted cycle must NOT pay the [N, M]
    MLP forward for a column multiplied by zero."""
    from gie_tpu.controller.cluster import FakeCluster
    from gie_tpu.runtime.options import Options
    from gie_tpu.runtime.runner import ExtProcServerRunner

    opts = Options(pool_name="p", enable_predictor=True)
    runner = ExtProcServerRunner(opts, FakeCluster())
    try:
        assert runner.trainer is not None      # admission path available
        assert runner.scheduler.predictor_fn is None   # no cycle cost
        assert runner.scheduler.base_latency_weight == 0.0
    finally:
        runner.stop()


def test_pool_aggregate_gauges_for_hpa():
    """Reference roadmap item 4 (HPA on aggregate load-balancer metrics):
    the /metrics exposition carries live pool aggregates computed from the
    datastore + metrics tensor at scrape time."""
    import numpy as np
    from prometheus_client import generate_latest

    from gie_tpu.controller.cluster import FakeCluster
    from gie_tpu.datastore.objects import EndpointPool, Pod
    from gie_tpu.runtime import metrics as own_metrics
    from gie_tpu.runtime.runner import ExtProcServerRunner
    from gie_tpu.sched import constants as C

    opts = Options(pool_name="p")
    runner = ExtProcServerRunner(opts, FakeCluster())
    try:
        runner.datastore.pool_set(
            EndpointPool({"app": "x"}, [8000], "default"))
        runner.datastore.pod_update_or_add(
            Pod(name="p0", labels={"app": "x"}, ip="10.1.0.1"))
        runner.datastore.pod_update_or_add(
            Pod(name="p1", labels={"app": "x"}, ip="10.1.0.2"))
        slots = [ep.slot for ep in runner.datastore.endpoints()]
        for s in slots:
            runner.metrics_store.update(
                s, {C.Metric.QUEUE_DEPTH: 7.0, C.Metric.KV_CACHE_UTIL: 0.5})

        snap = runner._pool_snapshot()
        assert snap["ready_endpoints"] == 2.0
        assert snap["queue_depth_total"] == pytest.approx(14.0)
        assert snap["kv_cache_util_mean"] == pytest.approx(0.5)
        assert snap["saturated_fraction"] == 0.0

        text = generate_latest(own_metrics.REGISTRY).decode()
        assert "gie_pool_endpoints 2.0" in text
        assert "gie_pool_queue_depth_total 14.0" in text

        # A second runner re-registers without duplicating collectors,
        # and the gauges follow the LATEST runner's snapshot.
        runner2 = ExtProcServerRunner(Options(pool_name="p2"), FakeCluster())
        try:
            text = generate_latest(own_metrics.REGISTRY).decode()
            assert "gie_pool_endpoints 0.0" in text
        finally:
            runner2.stop()
    finally:
        runner.stop()
