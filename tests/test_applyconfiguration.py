"""Apply-configuration builders + fake-clientset actions/reactors
(VERDICT r02 Missing #3: the client-go applyconfiguration and
clientset/versioned/fake analogues).

Reference shapes: client-go/applyconfiguration/api/v1/inferencepool.go
(With* builders), client-go/clientset/versioned/fake (action recording +
reactors)."""

import pytest

from gie_tpu.api import types as api
from gie_tpu.api.applyconfiguration import (
    EndpointPickerApply,
    InferencePoolApply,
    InferencePoolSpecApply,
    apply_pool_configuration,
    ssa_merge,
)
from gie_tpu.api.client import InferencePoolClient
from gie_tpu.controller.cluster import FakeCluster


def full_cfg(name="pool-a") -> InferencePoolApply:
    return InferencePoolApply(name, "default").with_spec(
        InferencePoolSpecApply()
        .with_selector({"app": "model"})
        .with_target_ports(8000, 8001)
        .with_endpoint_picker_ref(
            EndpointPickerApply()
            .with_name("epp")
            .with_kind("Service")
            .with_port(9002)
        )
    )


def test_builder_emits_sparse_dict():
    d = (
        InferencePoolApply("p", "ns")
        .with_spec(InferencePoolSpecApply().with_target_ports(8000))
        .to_dict()
    )
    assert d["metadata"] == {"name": "p", "namespace": "ns"}
    assert d["spec"] == {"targetPorts": [{"number": 8000}]}
    assert "selector" not in d["spec"]  # unset = not owned


def test_ssa_merge_semantics():
    base = {"spec": {"selector": {"matchLabels": {"app": "m"}},
                     "targetPorts": [{"number": 1}]},
            "metadata": {"name": "p"}}
    patch = {"spec": {"targetPorts": [{"number": 2}, {"number": 3}]}}
    merged = ssa_merge(base, patch)
    # maps deep-merge: selector untouched; lists replace atomically.
    assert merged["spec"]["selector"] == {"matchLabels": {"app": "m"}}
    assert merged["spec"]["targetPorts"] == [{"number": 2}, {"number": 3}]
    assert base["spec"]["targetPorts"] == [{"number": 1}]  # inputs untouched


def test_apply_creates_then_patches_preserving_unowned_fields():
    cluster = FakeCluster()
    client = InferencePoolClient(cluster)
    created = client.server_side_apply(full_cfg())
    assert [p.number for p in created.spec.targetPorts] == [8000, 8001]
    assert created.spec.selector.matchLabels == {"app": "model"}

    # Second apply owns ONLY targetPorts: selector + EPP ref survive.
    patch = InferencePoolApply("pool-a", "default").with_spec(
        InferencePoolSpecApply().with_target_ports(9000)
    )
    merged = client.server_side_apply(patch)
    assert [p.number for p in merged.spec.targetPorts] == [9000]
    assert merged.spec.selector.matchLabels == {"app": "model"}
    assert merged.spec.endpointPickerRef.name == "epp"


def test_apply_validates_like_admission():
    cluster = FakeCluster()
    client = InferencePoolClient(cluster)
    client.server_side_apply(full_cfg())
    dup_ports = InferencePoolApply("pool-a", "default").with_spec(
        InferencePoolSpecApply().with_target_ports(8000, 8000)
    )
    with pytest.raises(api.ValidationError):
        client.server_side_apply(dup_ports)
    # Store unchanged after rejection.
    assert [p.number for p in cluster.get_pool("default", "pool-a").spec.targetPorts] == [8000, 8001]


def test_apply_onto_missing_object_creates():
    pool = apply_pool_configuration(None, full_cfg("fresh"))
    assert pool.metadata.name == "fresh"
    assert [p.number for p in pool.spec.targetPorts] == [8000, 8001]


def test_fake_clientset_records_actions():
    cluster = FakeCluster()
    client = InferencePoolClient(cluster)
    client.server_side_apply(full_cfg())
    client.get("pool-a", "default")
    client.delete("pool-a", "default")
    verbs = [(v, r) for v, r, _ in cluster.actions]
    assert ("get", "inferencepools") in verbs
    assert ("apply", "inferencepools") in verbs
    assert ("delete", "inferencepools") in verbs
    keys = [k for _, r, k in cluster.actions if r == "inferencepools"]
    assert all(k == "default/pool-a" for k in keys)


def test_reactor_simulates_apiserver_conflict():
    """A reactor raising on apply = the client-go PrependReactor conflict
    pattern: the caller sees the error; the store is untouched."""
    cluster = FakeCluster()
    client = InferencePoolClient(cluster)

    class Conflict(Exception):
        pass

    calls = []

    def react(action):
        calls.append(action)
        raise Conflict("the object has been modified")

    cluster.add_reactor("apply", "inferencepools", react)
    with pytest.raises(Conflict):
        client.server_side_apply(full_cfg())
    assert calls and calls[0][0] == "apply"
    assert cluster.get_pool("default", "pool-a") is None


def test_reactor_can_fake_reads():
    cluster = FakeCluster()
    ghost = api.pool_from_dict({
        "apiVersion": f"{api.GROUP}/v1", "kind": "InferencePool",
        "metadata": {"name": "ghost", "namespace": "default"},
        "spec": {"targetPorts": [{"number": 1234}],
                 "selector": {"matchLabels": {}}},
    })
    cluster.add_reactor("get", "inferencepools",
                        lambda action: (True, ghost))
    got = InferencePoolClient(cluster).get("anything", "default")
    assert got is ghost
