"""TPOT-head training from the response stream (VERDICT r3 #7).

The TTFT half of the two-headed latency predictor trains at the
response-headers hop; these tests pin the OTHER half: token counts
harvested from the response body (SSE frame counting, usage-block parse,
transcoded Generate frames), the response-complete hook feeding
TPOT-masked observations, and the trained TPOT column steering the
pd decode pick.
"""

import json

import numpy as np

from gie_tpu.extproc import RoundRobinPicker, StreamingServer, pb
from gie_tpu.extproc.server import RequestContext
from gie_tpu.models.latency import (
    NUM_FEATURES,
    LatencyPredictor,
    OnlineTrainer,
)
from tests.test_extproc import FakeStream, headers_msg, make_ds


def _resp_body_msg(data: bytes, end: bool = False) -> pb.ProcessingRequest:
    return pb.ProcessingRequest(
        response_body=pb.HttpBody(body=data, end_of_stream=end)
    )


def _server(**kw) -> StreamingServer:
    return StreamingServer(make_ds(), RoundRobinPicker(), **kw)


def test_sse_frame_counting_with_split_marker():
    srv = _server()
    ctx = RequestContext()
    # 3 data frames, one marker split across the chunk boundary.
    srv._count_plain_tokens(ctx, b'data: {"c":1}\n\nda')
    srv._count_plain_tokens(ctx, b'ta: {"c":2}\n\ndata: {"c":3}\n\n')
    srv._finish_token_count(ctx)
    assert ctx.resp_tokens == 3


def test_done_sentinel_not_counted():
    srv = _server()
    ctx = RequestContext()
    srv._count_plain_tokens(ctx, b'data: {"c":1}\n\ndata: {"c":2}\n\n')
    srv._count_plain_tokens(ctx, b"data: [DONE]\n\n")
    srv._finish_token_count(ctx)
    assert ctx.resp_tokens == 2


def test_payload_containing_data_marker_not_counted():
    """A completion whose *text* contains "data:" must not inflate the
    frame count (VERDICT r4 #3): only line-anchored `data:` is a frame."""
    srv = _server()
    ctx = RequestContext()
    srv._count_plain_tokens(
        ctx,
        b'data: {"text":"the data: field and more data: here"}\n\n'
        b'data: {"text":"plain"}\n\n',
    )
    srv._finish_token_count(ctx)
    assert ctx.resp_tokens == 2


def test_payload_containing_done_sentinel_no_decrement():
    """"data: [DONE]" inside a completion's text is payload, not the
    stream-end sentinel — the decrement must not fire."""
    srv = _server()
    ctx = RequestContext()
    srv._count_plain_tokens(
        ctx, b'data: {"text":"say data: [DONE] verbatim"}\n\n'
    )
    srv._count_plain_tokens(ctx, b'data: {"text":"x"}\n\n')
    srv._finish_token_count(ctx)
    assert ctx.resp_tokens == 2


def test_first_frame_at_stream_start_counts():
    """The very first frame has no preceding newline; the virtual-anchor
    seed must count it — including a stream that is ONLY the sentinel."""
    srv = _server()
    ctx = RequestContext()
    srv._count_plain_tokens(ctx, b'data: {"c":1}\n\n')
    srv._finish_token_count(ctx)
    assert ctx.resp_tokens == 1

    ctx2 = RequestContext()
    srv._count_plain_tokens(ctx2, b"data: [DONE]\n\n")
    srv._finish_token_count(ctx2)
    assert ctx2.resp_tokens == 0


def test_crlf_terminated_frames_count_once_each():
    srv = _server()
    ctx = RequestContext()
    srv._count_plain_tokens(ctx, b'data: {"c":1}\r\n\r\ndata: {"c":2}\r')
    srv._count_plain_tokens(ctx, b'\n\r\ndata: [DONE]\r\n\r\n')
    srv._finish_token_count(ctx)
    assert ctx.resp_tokens == 2


def test_bare_done_line_after_empty_frame_no_decrement():
    """An empty data frame followed by a bare "[DONE]" line (which an SSE
    parser ignores) is not the sentinel — the decrement must not fire
    across line boundaries."""
    srv = _server()
    ctx = RequestContext()
    srv._count_plain_tokens(ctx, b'data: {"c":1}\n\ndata:\n\n[DONE]\n\n')
    srv._finish_token_count(ctx)
    assert ctx.resp_tokens == 2  # the real frame + the empty frame


def test_split_done_sentinel_still_decrements():
    """[DONE] split across chunk boundaries is contiguous in the rolling
    tail, so the anchored decrement still fires."""
    srv = _server()
    ctx = RequestContext()
    srv._count_plain_tokens(ctx, b'data: {"c":1}\n\ndata: [D')
    srv._count_plain_tokens(ctx, b'ONE]\n\n')
    srv._finish_token_count(ctx)
    assert ctx.resp_tokens == 1


def test_exact_4096_byte_untruncated_body_still_decrements():
    """A body of EXACTLY 4096 bytes that starts with the [DONE] sentinel
    is untruncated — the tail still IS the whole body, so the start-of-
    stream decrement must fire (ADVICE r5 #3: the old `len < 4096` test
    conflated this with a truncated tail)."""
    srv = _server()
    ctx = RequestContext()
    body = b"data: [DONE]\n\n" + b"x" * (4096 - 14)
    assert len(body) == 4096
    srv._count_plain_tokens(ctx, body)
    assert ctx.resp_tail_truncated is False
    srv._finish_token_count(ctx)
    assert ctx.resp_tokens == 0

    # The truncated twin: one byte longer, sentinel pushed off the start
    # of the retained tail window — the decrement must NOT fire on a
    # leading match that is no longer the stream start.
    ctx2 = RequestContext()
    srv._count_plain_tokens(ctx2, b"y" + body)
    assert ctx2.resp_tail_truncated is True


def test_usage_block_overrides_frame_count():
    srv = _server()
    ctx = RequestContext()
    body = json.dumps(
        {"choices": [{"text": "hi"}],
         "usage": {"prompt_tokens": 5, "completion_tokens": 42}}
    ).encode()
    srv._count_plain_tokens(ctx, body)
    srv._finish_token_count(ctx)
    assert ctx.resp_tokens == 42
    # A buffered JSON body is NOT generation-cadenced chunking.
    assert ctx.timing_is_generation is False


def test_sse_stream_marks_generation_timing():
    srv = _server()
    ctx = RequestContext()
    srv._count_plain_tokens(ctx, b'data: {"c":1}\n\ndata: {"c":2}\n\n')
    srv._finish_token_count(ctx)
    assert ctx.timing_is_generation is True


def test_response_complete_hook_fires_with_timing():
    seen = {}
    srv = _server(on_response_complete=lambda ctx: seen.update(
        tokens=ctx.resp_tokens, t0=ctx.resp_first_at, t1=ctx.resp_last_at))
    stream = FakeStream([
        headers_msg(end_of_stream=True),
        _resp_body_msg(b'data: {"c":1}\n\n'),
        _resp_body_msg(b'data: {"c":2}\n\n'),
        _resp_body_msg(b'data: {"c":3}\n\n', end=True),
    ])
    srv.process(stream)
    assert seen["tokens"] == 3
    assert seen["t1"] >= seen["t0"] > 0


def test_observe_response_complete_trains_tpot_head():
    """End to end through the picker: the hook must deposit a TPOT-masked
    observation whose weight vector trains ONLY the second head."""
    from types import SimpleNamespace

    from tests.test_batching_robustness import _stack

    trainer = OnlineTrainer(LatencyPredictor(), batch_size=8)
    sched, ds, ms, picker = _stack(n_pods=2)
    picker.trainer = trainer
    try:
        feats = np.zeros((NUM_FEATURES,), np.float32)
        ctx = SimpleNamespace(
            pick_result=SimpleNamespace(
                feedback=(feats, 1, 0.0, "10.9.0.2:8000")),
            served_hostport="10.9.0.2:8000",
            resp_tokens=11,
            resp_first_at=10.0,
            resp_last_at=10.5,   # 0.5 s over 10 intervals -> 50 ms/token
            timing_is_generation=True,
        )
        picker.observe_response_complete(ctx)
        assert trainer._n == 1
        np.testing.assert_allclose(trainer._targets[0], [0.0, 0.05])
        np.testing.assert_allclose(trainer._weights[0], [0.0, 1.0])

        # Failover guard: stream served by a different endpoint -> skip.
        ctx.served_hostport = "10.9.0.1:8000"
        picker.observe_response_complete(ctx)
        assert trainer._n == 1
        # Single-chunk response -> no interval -> skip.
        ctx.served_hostport = "10.9.0.2:8000"
        ctx.resp_tokens = 1
        picker.observe_response_complete(ctx)
        assert trainer._n == 1
        # Buffered JSON split across flushes: usage says 500 tokens but
        # the chunk spacing is network cadence -> must NOT train TPOT.
        ctx.resp_tokens = 500
        ctx.timing_is_generation = False
        picker.observe_response_complete(ctx)
        assert trainer._n == 1
    finally:
        picker.close()


def test_trained_tpot_column_steers_pd_decode_pick():
    """BASELINE configs[3] + pd: train the TPOT head so slot 0 is the
    fast decoder, then the pd decode pick must prefer it for long-decode
    requests (the latency column is live in the decode blend; prefix/
    session are the only columns dropped there)."""
    import functools

    import jax

    from gie_tpu.sched import constants as C
    from gie_tpu.sched.profile import ProfileConfig, scheduling_cycle
    from gie_tpu.sched.types import SchedState, Weights
    from gie_tpu.models.latency import predictor_score_fn
    from gie_tpu.utils.testing import make_endpoints, make_requests

    predictor = LatencyPredictor()
    trainer = OnlineTrainer(predictor, batch_size=64)
    feats = np.zeros((NUM_FEATURES,), np.float32)
    # Identical metrics everywhere: only the slot embedding can learn the
    # difference. Slot 0 decodes at 10 ms/token, slot 1 at 200 ms/token.
    rng = np.random.default_rng(0)
    for _ in range(256):
        trainer.observe(feats, ttft_s=0.1,
                        tpot_s=0.01 + rng.normal(0, 1e-4), slot=0)
        trainer.observe(feats, ttft_s=0.1,
                        tpot_s=0.20 + rng.normal(0, 1e-4), slot=1)
    for _ in range(60):
        trainer.train(steps=5)
    pred = np.asarray(predictor.predict(
        trainer.params,
        np.stack([feats, feats]),
        np.asarray([0, 1], np.int32),
    ))
    assert pred[0, 1] < pred[1, 1], "TPOT head failed to separate slots"

    cfg = ProfileConfig(pd_disaggregation=True, enable_prefix=False,
                        enable_session=False)
    fn = jax.jit(functools.partial(
        scheduling_cycle, cfg=cfg,
        predictor_fn=predictor_score_fn(predictor)))
    eps = make_endpoints(
        4, queue=[0, 0, 0, 0], kv=[0.1, 0.1, 0.1, 0.1],
        role=[int(C.Role.DECODE), int(C.Role.DECODE),
              int(C.Role.PREFILL), int(C.Role.PREFILL)],
        m_slots=64)
    reqs = make_requests(8, prompt_len=[2048.0] * 8, m_slots=64)
    reqs = reqs.replace(decode_len=np.full((8,), 4096.0, np.float32))
    weights = Weights.default().replace(latency=np.float32(2.0))
    res, _ = fn(SchedState.init(m=64), reqs, eps, weights,
                jax.random.PRNGKey(0), trainer.params)
    decode_picks = np.asarray(res.indices[:, 0])
    assert (decode_picks == 0).all(), (
        f"decode pick ignored the live TPOT column: {decode_picks}")
