"""Distributed-correctness guarantee: the dp-sharded scheduling cycle must
produce IDENTICAL results to the single-device cycle — sharding is a layout
choice, never a semantics change."""

import functools

import jax
import numpy as np
import pytest

from gie_tpu.models.latency import LatencyPredictor, predictor_score_fn
from gie_tpu.parallel.mesh import make_mesh, sharded_cycle
from gie_tpu.sched.profile import ProfileConfig, scheduling_cycle
from gie_tpu.sched.types import SchedState, Weights
from gie_tpu.utils.testing import make_endpoints, make_requests


@pytest.mark.parametrize("picker", ["topk", "sinkhorn"])
def test_sharded_cycle_identical_to_single_device(picker):
    assert len(jax.devices()) >= 8
    cfg = ProfileConfig(picker=picker)
    rng = np.random.default_rng(0)
    m = 32
    eps = make_endpoints(
        m,
        queue=rng.integers(0, 30, m).tolist(),
        kv=rng.uniform(0, 0.9, m).tolist(),
    )
    prompts = [b"SYSTEM %d " % (i % 4) * 40 + b"q%d" % i for i in range(64)]
    reqs = make_requests(64, prompts=prompts)
    state = SchedState.init()
    weights = Weights.default()
    key = jax.random.PRNGKey(7)

    single = jax.jit(
        functools.partial(scheduling_cycle, cfg=cfg, predictor_fn=None)
    )
    r1, s1 = single(state, reqs, eps, weights, key, None)

    mesh = make_mesh(8)
    sharded = sharded_cycle(mesh, cfg, None)
    r2, s2 = sharded(SchedState.init(), reqs, eps, weights, key, None)

    np.testing.assert_array_equal(np.asarray(r1.indices), np.asarray(r2.indices))
    np.testing.assert_array_equal(np.asarray(r1.status), np.asarray(r2.status))
    np.testing.assert_allclose(
        np.asarray(s1.assumed_load), np.asarray(s2.assumed_load), atol=1e-6
    )
    # Prefix-table updates must agree too (dense scatters across shards).
    np.testing.assert_array_equal(
        np.asarray(s1.prefix.keys), np.asarray(s2.prefix.keys)
    )
    np.testing.assert_array_equal(
        np.asarray(s1.prefix.present), np.asarray(s2.prefix.present)
    )


def test_sharded_cycle_with_predictor_column():
    assert len(jax.devices()) >= 8
    predictor = LatencyPredictor()
    params = predictor.init(jax.random.PRNGKey(0))
    cfg = ProfileConfig()
    fn = predictor_score_fn(predictor)
    reqs = make_requests(16, prompt_len=[256.0] * 16)
    eps = make_endpoints(8, queue=[0, 1, 2, 3, 4, 5, 6, 7])
    weights = Weights.default()
    key = jax.random.PRNGKey(1)

    single = jax.jit(functools.partial(scheduling_cycle, cfg=cfg, predictor_fn=fn))
    r1, _ = single(SchedState.init(), reqs, eps, weights, key, params)
    mesh = make_mesh(8)
    sharded = sharded_cycle(mesh, cfg, fn)
    r2, _ = sharded(SchedState.init(), reqs, eps, weights, key, params)
    np.testing.assert_array_equal(np.asarray(r1.indices), np.asarray(r2.indices))
