"""Distributed-correctness guarantee: the dp-sharded scheduling cycle must
produce IDENTICAL results to the single-device cycle — sharding is a layout
choice, never a semantics change."""

import functools

import jax
import numpy as np
import pytest

from gie_tpu.models.latency import LatencyPredictor, predictor_score_fn
from gie_tpu.parallel.mesh import make_mesh, sharded_cycle
from gie_tpu.sched.profile import ProfileConfig, scheduling_cycle
from gie_tpu.sched.types import SchedState, Weights
from gie_tpu.utils.testing import make_endpoints, make_requests


@pytest.mark.parametrize("picker", ["topk", "sinkhorn"])
def test_sharded_cycle_identical_to_single_device(picker):
    assert len(jax.devices()) >= 8
    cfg = ProfileConfig(picker=picker)
    rng = np.random.default_rng(0)
    m = 32
    eps = make_endpoints(
        m,
        queue=rng.integers(0, 30, m).tolist(),
        kv=rng.uniform(0, 0.9, m).tolist(),
    )
    prompts = [b"SYSTEM %d " % (i % 4) * 40 + b"q%d" % i for i in range(64)]
    reqs = make_requests(64, prompts=prompts)
    state = SchedState.init()
    weights = Weights.default()
    key = jax.random.PRNGKey(7)

    single = jax.jit(
        functools.partial(scheduling_cycle, cfg=cfg, predictor_fn=None)
    )
    r1, s1 = single(state, reqs, eps, weights, key, None)

    mesh = make_mesh(8)
    sharded = sharded_cycle(mesh, cfg, None)
    r2, s2 = sharded(SchedState.init(), reqs, eps, weights, key, None)

    np.testing.assert_array_equal(np.asarray(r1.indices), np.asarray(r2.indices))
    np.testing.assert_array_equal(np.asarray(r1.status), np.asarray(r2.status))
    np.testing.assert_allclose(
        np.asarray(s1.assumed_load), np.asarray(s2.assumed_load), atol=1e-6
    )
    # Prefix-table updates must agree too (dense scatters across shards).
    np.testing.assert_array_equal(
        np.asarray(s1.prefix.keys), np.asarray(s2.prefix.keys)
    )
    np.testing.assert_array_equal(
        np.asarray(s1.prefix.present), np.asarray(s2.prefix.present)
    )


def test_sharded_cycle_with_predictor_column():
    assert len(jax.devices()) >= 8
    predictor = LatencyPredictor()
    params = predictor.init(jax.random.PRNGKey(0))
    cfg = ProfileConfig()
    fn = predictor_score_fn(predictor)
    reqs = make_requests(16, prompt_len=[256.0] * 16)
    eps = make_endpoints(8, queue=[0, 1, 2, 3, 4, 5, 6, 7])
    weights = Weights.default()
    key = jax.random.PRNGKey(1)

    single = jax.jit(functools.partial(scheduling_cycle, cfg=cfg, predictor_fn=fn))
    r1, _ = single(SchedState.init(), reqs, eps, weights, key, params)
    mesh = make_mesh(8)
    sharded = sharded_cycle(mesh, cfg, fn)
    r2, _ = sharded(SchedState.init(), reqs, eps, weights, key, params)
    np.testing.assert_array_equal(np.asarray(r1.indices), np.asarray(r2.indices))


def test_scheduler_facade_with_mesh_matches_single_device():
    """The production path: Scheduler(mesh=...) — the --mesh-devices flag —
    must return the same picks as the unsharded facade, including across
    state-carrying successive batches and the small-batch bucket floor
    (batches pad up to a dp-divisible bucket)."""
    from gie_tpu.sched import Scheduler

    assert len(jax.devices()) >= 8
    cfg = ProfileConfig()
    rng = np.random.default_rng(3)
    m = 16
    eps = make_endpoints(
        m,
        queue=rng.integers(0, 30, m).tolist(),
        kv=rng.uniform(0, 0.9, m).tolist(),
    )
    plain = Scheduler(cfg, seed=5)
    meshed = Scheduler(cfg, seed=5, mesh=make_mesh(8))
    assert meshed._min_bucket == 4  # dp axis of the (4, 2) mesh

    for wave in range(3):
        prompts = [b"S%d " % (i % 4) * 30 + b"w%d q%d" % (wave, i)
                   for i in range(24)]
        reqs = make_requests(24, prompts=prompts)
        r1 = plain.pick(reqs, eps)
        r2 = meshed.pick(reqs, eps)
        np.testing.assert_array_equal(
            np.asarray(r1.indices), np.asarray(r2.indices))
        np.testing.assert_array_equal(
            np.asarray(r1.status), np.asarray(r2.status))
    np.testing.assert_allclose(
        plain.snapshot_assumed_load(), meshed.snapshot_assumed_load(),
        atol=1e-5)
    # A 3-request batch pads to the bucket floor and still round-trips.
    small = meshed.pick(make_requests(3), eps)
    assert np.asarray(small.indices).shape[0] == 3


def test_mesh_guardrails():
    """Clear startup errors instead of cryptic jit crashes: non-power-of-two
    dp axes are rejected by the Scheduler, over-requested meshes by
    make_mesh, and --mesh-devices validation catches both early."""
    from gie_tpu.runtime.options import Options
    from gie_tpu.sched import Scheduler

    with pytest.raises(ValueError, match="power of two"):
        Scheduler(ProfileConfig(), mesh=make_mesh(6, tp=2))  # dp=3
    with pytest.raises(ValueError, match="available"):
        make_mesh(len(jax.devices()) + 1)
    opts = Options(pool_name="p", mesh_devices=6)
    with pytest.raises(ValueError, match="power of two"):
        opts.validate()
    Options(pool_name="p", mesh_devices=8).validate()


def test_pd_cycle_sharded_equivalence():
    """The dual prefill/decode pick must survive dp-sharding bit-for-bit
    (both picks, status merge, and split load charging)."""
    from gie_tpu.sched import constants as C

    assert len(jax.devices()) >= 8
    cfg = ProfileConfig(pd_disaggregation=True)
    R = C.Role
    roles = [R.PREFILL, R.PREFILL, R.DECODE, R.DECODE, R.BOTH, R.BOTH,
             R.PREFILL, R.DECODE]
    rng = np.random.default_rng(11)
    eps = make_endpoints(
        8, queue=rng.integers(0, 30, 8).tolist(),
        kv=rng.uniform(0, 0.9, 8).tolist(), role=roles)
    prompts = [b"PD %d " % (i % 3) * 30 + b"q%d" % i for i in range(32)]
    reqs = make_requests(32, prompts=prompts)
    weights = Weights.default()
    key = jax.random.PRNGKey(13)

    single = jax.jit(
        functools.partial(scheduling_cycle, cfg=cfg, predictor_fn=None))
    r1, s1 = single(SchedState.init(), reqs, eps, weights, key, None)
    sharded = sharded_cycle(make_mesh(8), cfg, None)
    r2, s2 = sharded(SchedState.init(), reqs, eps, weights, key, None)

    np.testing.assert_array_equal(
        np.asarray(r1.indices), np.asarray(r2.indices))
    np.testing.assert_array_equal(
        np.asarray(r1.prefill), np.asarray(r2.prefill))
    np.testing.assert_array_equal(np.asarray(r1.status), np.asarray(r2.status))
    np.testing.assert_allclose(
        np.asarray(s1.assumed_load), np.asarray(s2.assumed_load), atol=1e-6)
