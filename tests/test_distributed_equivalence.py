"""Distributed-correctness guarantee: the dp-sharded scheduling cycle must
produce IDENTICAL results to the single-device cycle — sharding is a layout
choice, never a semantics change."""

import functools

import jax
import numpy as np
import pytest

from gie_tpu.models.latency import LatencyPredictor, predictor_score_fn
from gie_tpu.parallel.mesh import make_mesh, sharded_cycle
from gie_tpu.sched.profile import ProfileConfig, scheduling_cycle
from gie_tpu.sched.types import SchedState, Weights
from gie_tpu.utils.testing import make_endpoints, make_requests


@pytest.mark.parametrize("picker", ["topk", "sinkhorn"])
def test_sharded_cycle_identical_to_single_device(picker):
    assert len(jax.devices()) >= 8
    cfg = ProfileConfig(picker=picker)
    rng = np.random.default_rng(0)
    m = 32
    eps = make_endpoints(
        m,
        queue=rng.integers(0, 30, m).tolist(),
        kv=rng.uniform(0, 0.9, m).tolist(),
    )
    prompts = [b"SYSTEM %d " % (i % 4) * 40 + b"q%d" % i for i in range(64)]
    reqs = make_requests(64, prompts=prompts)
    state = SchedState.init()
    weights = Weights.default()
    key = jax.random.PRNGKey(7)

    single = jax.jit(
        functools.partial(scheduling_cycle, cfg=cfg, predictor_fn=None)
    )
    r1, s1 = single(state, reqs, eps, weights, key, None)

    mesh = make_mesh(8)
    sharded = sharded_cycle(mesh, cfg, None)
    r2, s2 = sharded(SchedState.init(), reqs, eps, weights, key, None)

    np.testing.assert_array_equal(np.asarray(r1.indices), np.asarray(r2.indices))
    np.testing.assert_array_equal(np.asarray(r1.status), np.asarray(r2.status))
    np.testing.assert_allclose(
        np.asarray(s1.assumed_load), np.asarray(s2.assumed_load), atol=1e-6
    )
    # Prefix-table updates must agree too (dense scatters across shards).
    np.testing.assert_array_equal(
        np.asarray(s1.prefix.keys), np.asarray(s2.prefix.keys)
    )
    np.testing.assert_array_equal(
        np.asarray(s1.prefix.present), np.asarray(s2.prefix.present)
    )


def test_sharded_cycle_with_predictor_column():
    assert len(jax.devices()) >= 8
    predictor = LatencyPredictor()
    params = predictor.init(jax.random.PRNGKey(0))
    cfg = ProfileConfig()
    fn = predictor_score_fn(predictor)
    reqs = make_requests(16, prompt_len=[256.0] * 16)
    eps = make_endpoints(8, queue=[0, 1, 2, 3, 4, 5, 6, 7])
    weights = Weights.default()
    key = jax.random.PRNGKey(1)

    single = jax.jit(functools.partial(scheduling_cycle, cfg=cfg, predictor_fn=fn))
    r1, _ = single(SchedState.init(), reqs, eps, weights, key, params)
    mesh = make_mesh(8)
    sharded = sharded_cycle(mesh, cfg, fn)
    r2, _ = sharded(SchedState.init(), reqs, eps, weights, key, params)
    np.testing.assert_array_equal(np.asarray(r1.indices), np.asarray(r2.indices))


def test_scheduler_facade_with_mesh_matches_single_device():
    """The production path: Scheduler(mesh=...) — the --mesh-devices flag —
    must return the same picks as the unsharded facade, including across
    state-carrying successive batches and the small-batch bucket floor
    (batches pad up to a dp-divisible bucket)."""
    from gie_tpu.sched import Scheduler

    assert len(jax.devices()) >= 8
    cfg = ProfileConfig()
    rng = np.random.default_rng(3)
    m = 16
    eps = make_endpoints(
        m,
        queue=rng.integers(0, 30, m).tolist(),
        kv=rng.uniform(0, 0.9, m).tolist(),
    )
    plain = Scheduler(cfg, seed=5)
    meshed = Scheduler(cfg, seed=5, mesh=make_mesh(8))
    assert meshed._min_bucket == 4  # dp axis of the (4, 2) mesh

    for wave in range(3):
        prompts = [b"S%d " % (i % 4) * 30 + b"w%d q%d" % (wave, i)
                   for i in range(24)]
        reqs = make_requests(24, prompts=prompts)
        r1 = plain.pick(reqs, eps)
        r2 = meshed.pick(reqs, eps)
        np.testing.assert_array_equal(
            np.asarray(r1.indices), np.asarray(r2.indices))
        np.testing.assert_array_equal(
            np.asarray(r1.status), np.asarray(r2.status))
    np.testing.assert_allclose(
        plain.snapshot_assumed_load(), meshed.snapshot_assumed_load(),
        atol=1e-5)
    # A 3-request batch pads to the bucket floor and still round-trips.
    small = meshed.pick(make_requests(3), eps)
    assert np.asarray(small.indices).shape[0] == 3


def test_mesh_guardrails():
    """Clear startup errors instead of cryptic jit crashes: non-power-of-two
    dp axes are rejected by the Scheduler, over-requested meshes by
    make_mesh, and --mesh-devices validation catches both early."""
    from gie_tpu.runtime.options import Options
    from gie_tpu.sched import Scheduler

    with pytest.raises(ValueError, match="power of two"):
        Scheduler(ProfileConfig(), mesh=make_mesh(6, tp=2))  # dp=3
    with pytest.raises(ValueError, match="available"):
        make_mesh(len(jax.devices()) + 1)
    opts = Options(pool_name="p", mesh_devices=6)
    with pytest.raises(ValueError, match="power of two"):
        opts.validate()
    Options(pool_name="p", mesh_devices=8).validate()


def _loaded_pool(m_valid: int, m_slots: int, seed: int):
    """A contended pool (queues near the limit, mixed KV) so sinkhorn's
    capacity caps BIND and the warm-start gate engages — an idle fleet
    solves trivially and would make equivalence vacuous."""
    rng = np.random.default_rng(seed)
    return make_endpoints(
        m_valid,
        queue=rng.integers(40, 120, m_valid).tolist(),
        kv=rng.uniform(0.1, 0.9, m_valid).tolist(),
        m_slots=m_slots,
    )


@pytest.mark.parametrize("n_mesh", [1, 2, 4, 8])
@pytest.mark.parametrize("picker", ["topk", "sinkhorn", "random"])
def test_mesh_size_equivalence_matrix(n_mesh, picker):
    """The pinned property behind "scheduler scales with chips": for EVERY
    mesh size x picker, the sharded cycle is bit-identical to the
    single-device cycle — including a valid-endpoint count (37) that no
    tp axis divides, so shards see ragged padding-lane mixes, and a
    second wave threaded through identical carried state (covering the
    warm-start duals and prefix scatters, not just a cold solve)."""
    assert len(jax.devices()) >= 8
    cfg = ProfileConfig(picker=picker)
    eps = _loaded_pool(37, 64, seed=21)
    state = SchedState.init(m=64)
    weights = Weights.default()
    single = jax.jit(
        functools.partial(scheduling_cycle, cfg=cfg, predictor_fn=None))
    sharded = sharded_cycle(make_mesh(n_mesh), cfg, None)

    for wave in range(2):
        prompts = [b"MAT %d " % (i % 4) * 30 + b"w%d q%d" % (wave, i)
                   for i in range(64)]
        reqs = make_requests(64, prompts=prompts, m_slots=64)
        key = jax.random.PRNGKey(100 + wave)
        r1, s1 = single(state, reqs, eps, weights, key, None)
        r2, s2 = sharded(state, reqs, eps, weights, key, None)
        np.testing.assert_array_equal(
            np.asarray(r1.indices), np.asarray(r2.indices))
        np.testing.assert_array_equal(
            np.asarray(r1.status), np.asarray(r2.status))
        np.testing.assert_array_equal(
            np.asarray(s1.ot_v), np.asarray(s2.ot_v))
        np.testing.assert_array_equal(
            np.asarray(s1.prefix.keys), np.asarray(s2.prefix.keys))
        np.testing.assert_array_equal(
            np.asarray(s1.prefix.present), np.asarray(s2.prefix.present))
        np.testing.assert_allclose(
            np.asarray(s1.assumed_load), np.asarray(s2.assumed_load),
            atol=1e-6)
        # Both paths advance from the SAME state so every wave isolates
        # its own equivalence (scatter-order float drift in assumed_load
        # is tolerance-bounded, not compounded).
        state = s1
    # Not vacuous: some picks landed and (sinkhorn) duals evolved.
    assert (np.asarray(r1.indices[:, 0]) >= 0).any()
    if picker == "sinkhorn":
        assert not np.allclose(np.asarray(s1.ot_v), 1.0)


@pytest.mark.parametrize("tp", [1, 2, 4, 8])
def test_mesh_axis_extremes_equivalence(tp):
    """The same guarantee at the mesh-shape extremes: all-dp (tp=1), the
    default split, and all-tp (tp=8 — endpoint words below the shard
    floor fall back to replicated prefix bits, picks still identical)."""
    assert len(jax.devices()) >= 8
    cfg = ProfileConfig(picker="sinkhorn")
    eps = _loaded_pool(37, 64, seed=22)
    reqs = make_requests(
        32, prompts=[b"EX %d " % (i % 3) * 25 + b"q%d" % i
                     for i in range(32)], m_slots=64)
    weights = Weights.default()
    key = jax.random.PRNGKey(5)
    single = jax.jit(
        functools.partial(scheduling_cycle, cfg=cfg, predictor_fn=None))
    r1, s1 = single(SchedState.init(m=64), reqs, eps, weights, key, None)
    sharded = sharded_cycle(make_mesh(8, tp=tp), cfg, None)
    r2, s2 = sharded(SchedState.init(m=64), reqs, eps, weights, key, None)
    np.testing.assert_array_equal(np.asarray(r1.indices), np.asarray(r2.indices))
    np.testing.assert_array_equal(np.asarray(r1.status), np.asarray(r2.status))
    np.testing.assert_array_equal(np.asarray(s1.ot_v), np.asarray(s2.ot_v))


def test_warm_start_duals_sharded_parity():
    """ISSUE 15 satellite: the sinkhorn warm-start duals (ot_v) must flow
    through the sharded cycle with an EXPLICIT sharding and come back
    bit-identical to the single-device iterates, wave after wave — the
    per-shard-dual divergence was the repo's standing tier-1 failure."""
    from gie_tpu.parallel.mesh import state_shardings

    assert len(jax.devices()) >= 8
    mesh = make_mesh(8)
    # The duals' sharding is explicit (tp), never implicit replication.
    st_sh = state_shardings(mesh)
    assert st_sh.ot_v.spec == jax.sharding.PartitionSpec("tp")
    assert st_sh.assumed_load.spec == jax.sharding.PartitionSpec("tp")

    cfg = ProfileConfig(picker="sinkhorn")
    eps = _loaded_pool(48, 64, seed=23)
    weights = Weights.default()
    single = jax.jit(
        functools.partial(scheduling_cycle, cfg=cfg, predictor_fn=None))
    sharded = sharded_cycle(mesh, cfg, None)
    state = SchedState.init(m=64)
    iterates = []
    for wave in range(3):
        reqs = make_requests(
            64, prompts=[b"WS %d " % (i % 5) * 20 + b"w%d r%d" % (wave, i)
                         for i in range(64)], m_slots=64)
        key = jax.random.PRNGKey(wave)
        r1, s1 = single(state, reqs, eps, weights, key, None)
        r2, s2 = sharded(state, reqs, eps, weights, key, None)
        np.testing.assert_array_equal(
            np.asarray(s1.ot_v), np.asarray(s2.ot_v),
            err_msg=f"warm-start dual iterates diverged at wave {wave}")
        np.testing.assert_array_equal(
            np.asarray(r1.indices), np.asarray(r2.indices))
        iterates.append(np.asarray(s1.ot_v))
        state = s1
    # The warm start is live: iterates evolve across waves (the gate
    # would freeze them at ones on an idle fleet).
    assert not np.array_equal(iterates[0], iterates[1])


def test_prefix_presence_tp_sharded_never_replicated():
    """ISSUE 18 satellite (closes the PR 15 residual): the packed
    prefix-presence matrix tp-shards at EVERY power-of-two mesh size —
    on the word axis while the smallest bucket's word count divides tp,
    on the table-slot axis beyond that — never silently replicating the
    32768-row table per device."""
    from jax.sharding import PartitionSpec as P

    from gie_tpu.parallel.mesh import state_shardings
    from gie_tpu.sched import constants as C

    assert len(jax.devices()) >= 8
    words = C.M_BUCKETS[0] // 32
    for tp in (1, 2, 4, 8):
        spec = state_shardings(make_mesh(tp, tp=tp)).prefix.present.spec
        assert spec != P(), f"present replicated at tp={tp}"
        if words % tp == 0:
            assert spec == P(None, "tp"), (tp, spec)
        else:
            assert spec == P("tp", None), (tp, spec)


def test_pd_cycle_sharded_equivalence():
    """The dual prefill/decode pick must survive dp-sharding bit-for-bit
    (both picks, status merge, and split load charging)."""
    from gie_tpu.sched import constants as C

    assert len(jax.devices()) >= 8
    cfg = ProfileConfig(pd_disaggregation=True)
    R = C.Role
    roles = [R.PREFILL, R.PREFILL, R.DECODE, R.DECODE, R.BOTH, R.BOTH,
             R.PREFILL, R.DECODE]
    rng = np.random.default_rng(11)
    eps = make_endpoints(
        8, queue=rng.integers(0, 30, 8).tolist(),
        kv=rng.uniform(0, 0.9, 8).tolist(), role=roles)
    prompts = [b"PD %d " % (i % 3) * 30 + b"q%d" % i for i in range(32)]
    reqs = make_requests(32, prompts=prompts)
    weights = Weights.default()
    key = jax.random.PRNGKey(13)

    single = jax.jit(
        functools.partial(scheduling_cycle, cfg=cfg, predictor_fn=None))
    r1, s1 = single(SchedState.init(), reqs, eps, weights, key, None)
    sharded = sharded_cycle(make_mesh(8), cfg, None)
    r2, s2 = sharded(SchedState.init(), reqs, eps, weights, key, None)

    np.testing.assert_array_equal(
        np.asarray(r1.indices), np.asarray(r2.indices))
    np.testing.assert_array_equal(
        np.asarray(r1.prefill), np.asarray(r2.prefill))
    np.testing.assert_array_equal(np.asarray(r1.status), np.asarray(r2.status))
    np.testing.assert_allclose(
        np.asarray(s1.assumed_load), np.asarray(s2.assumed_load), atol=1e-6)
