"""Policy-search harness tests (gie_tpu/storm/search.py; gie-twin,
docs/STORM.md "policy search").

Fast tier: the grid/assignment/schema machinery. Slow tier (run by
``make storm-search-smoke``): the bounded 8-config smoke search over
storm-search-smoke, asserting the leaderboard validates and the
hand-swept ladder calibration (cached_kv_weight=8, wrr_alpha=1 —
docs/RESILIENCE.md) re-derives into the top half."""

from __future__ import annotations

import json

import pytest

from gie_tpu.storm import search


# --------------------------------------------------------------------------
# Grid + assignment machinery
# --------------------------------------------------------------------------


def test_expand_grid_is_a_full_product_in_order():
    grid = search.expand_grid({
        "ladder.cached_kv_weight": [0.0, 8.0],
        "breaker.open_after": [2, 4, 8],
    })
    assert len(grid) == 6
    assert grid[0] == {"ladder.cached_kv_weight": 0.0,
                      "breaker.open_after": 2}
    assert grid[-1] == {"ladder.cached_kv_weight": 8.0,
                       "breaker.open_after": 8}
    with pytest.raises(ValueError, match="empty search space"):
        search.expand_grid({})
    with pytest.raises(ValueError, match="non-empty value list"):
        search.expand_grid({"ladder.cached_kv_weight": []})
    with pytest.raises(ValueError, match="group"):
        search.expand_grid({"nope.x": [1]})
    with pytest.raises(ValueError, match="group"):
        search.expand_grid({"cached_kv_weight": [1]})


def test_apply_assignment_builds_the_engine_config():
    from gie_tpu.storm.engine import DEFAULT_BREAKER, EngineConfig

    cfg = search.apply_assignment(None, {
        "ladder.cached_kv_weight": 2.0,
        "ladder.wrr_queue_alpha": 4.0,
        "breaker.open_after": 7,
        "outlier.ratio": 2.5,
        "autoscale.shed_high_per_s": 3.0,
        "engine.queue_limit": 5.0,
    })
    assert isinstance(cfg, EngineConfig)
    assert cfg.ladder.cached_kv_weight == 2.0
    assert cfg.ladder.wrr_queue_alpha == 4.0
    assert cfg.breaker.open_after == 7
    # Unset breaker fields inherit the engine default, not the library
    # default (the search must perturb the config a storm actually runs).
    assert cfg.breaker.open_s == DEFAULT_BREAKER.open_s
    assert cfg.outlier is not None and cfg.outlier.ratio == 2.5
    assert cfg.autoscale_shed_high_per_s == 3.0
    assert cfg.queue_limit == 5.0


def test_apply_assignment_rejects_unknown_knobs_loudly():
    with pytest.raises(ValueError, match="ladder"):
        search.apply_assignment(None, {"ladder.not_a_field": 1})
    with pytest.raises(ValueError, match="not searchable"):
        search.apply_assignment(None, {"engine.serve_timeout_s": 1})
    with pytest.raises(ValueError, match="group"):
        search.apply_assignment(None, {"flat": 1})


def test_score_key_orders_goodput_then_slo_then_p99():
    a = {"goodput_tokens_per_s": 100.0, "slo_attainment": 0.9,
         "ttft_p99_s": 1.0}
    b = {"goodput_tokens_per_s": 90.0, "slo_attainment": 1.0,
         "ttft_p99_s": 0.5}
    c = {"goodput_tokens_per_s": 100.0, "slo_attainment": 0.9,
         "ttft_p99_s": 2.0}
    d = {"goodput_tokens_per_s": 100.0, "slo_attainment": 0.9,
         "ttft_p99_s": None}  # no completions: worst of the ties
    ranked = sorted([a, b, c, d], key=search._score_key, reverse=True)
    assert ranked == [a, c, d, b]


def test_validate_rejects_malformed_leaderboards():
    with pytest.raises(ValueError, match="schema"):
        search.validate({"schema": "nope"})
    with pytest.raises(ValueError, match="leaderboard"):
        search.validate({"schema": search.SCHEMA, "leaderboard": []})
    row = {f: 0 for f in search.REQUIRED_ROW_FIELDS}
    row["rank"] = 1
    with pytest.raises(ValueError, match="ranks"):
        search.validate({
            "schema": search.SCHEMA, "rounds": [{}],
            "leaderboard": [dict(row), dict(row)]})  # ranks 1,1 not 1,2
    bad = dict(row)
    del bad["goodput_tokens_per_s"]
    with pytest.raises(ValueError, match="missing fields"):
        search.validate({
            "schema": search.SCHEMA, "rounds": [{}], "leaderboard": [bad]})


def test_search_arg_validation():
    with pytest.raises(ValueError, match="exactly one"):
        search.search("storm-search-smoke")
    with pytest.raises(ValueError, match="exactly one"):
        search.search("storm-search-smoke", space={"ladder.x": [1]},
                      configs=[{}])
    with pytest.raises(ValueError, match="rounds"):
        search.search("storm-search-smoke",
                      space={"ladder.cached_kv_weight": [1.0]}, rounds=0)
    with pytest.raises(ValueError, match="drive.storm"):
        search.search("mixed-soak", space={"ladder.cached_kv_weight": [1.0]})


def test_smoke_scenario_ships_and_compiles():
    from gie_tpu.resilience import scenarios
    from gie_tpu.storm import shapes as S

    scn = scenarios.load(search.SMOKE_SCENARIO)
    assert scn.rules, "the smoke storm needs its rung-forcing chaos"
    prog = S.program_from_drive(scn.drive["storm"], seed=scn.seed)
    a, b = prog.compile(), prog.compile()
    assert a.fingerprint() == b.fingerprint()
    assert len(search.expand_grid(search.SMOKE_SPACE)) == 8


# --------------------------------------------------------------------------
# The smoke search itself (make storm-search-smoke; slow tier)
# --------------------------------------------------------------------------


@pytest.mark.slow
def test_storm_search_smoke_rederives_ladder_calibration(tmp_path, capsys):
    """The bounded 8-config grid + successive-halving search over the
    flash-crowd smoke storm, driven through the CLI entry point
    (python -m gie_tpu.storm.search): the leaderboard JSON validates,
    ranks are a clean 1..8, per-round history shows the halving, and
    the hand-swept ladder calibration (cached_kv_weight=8, wrr_alpha=1)
    lands in the top half — the harness re-derives what PR 10/11 swept
    by hand."""
    out = tmp_path / "leaderboard.json"
    rc = search.main(["--out", str(out)])
    assert rc == 0
    # The CLI prints the artifact JSON on stdout AND writes --out.
    printed = json.loads(capsys.readouterr().out)
    artifact = json.loads(out.read_text(encoding="utf-8"))
    assert printed["leaderboard"] == artifact["leaderboard"]
    search.validate(artifact)
    assert artifact["n_configs"] == 8
    assert artifact["virtual_time"] is True
    assert len(artifact["rounds"]) == 2
    # Successive halving: round 1 evaluated half the grid, twice as long.
    assert artifact["rounds"][0]["evaluated"] == 8
    assert artifact["rounds"][1]["evaluated"] == 4
    assert (artifact["rounds"][1]["duration_s"]
            == 2 * artifact["rounds"][0]["duration_s"])
    rank = search.rank_of(artifact, search.SMOKE_KNOWN_GOOD)
    assert rank is not None, "the known-good config fell off the board"
    assert rank <= len(artifact["leaderboard"]) // 2, (
        f"known-good ladder defaults ranked {rank} — the search "
        f"contradicts the hand-swept calibration: "
        f"{artifact['leaderboard']}")
