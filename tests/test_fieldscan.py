"""Field-scan parity suite (ISSUE 5): the native JSON scanner must agree
with json.loads-derived extraction on every input it claims to handle.

The contract (native/jsonscan.cc): for any body where scan_native returns
a FieldScan, that FieldScan MUST equal scan_py's (the single honest
json.loads reference). Returning None (fallback) is always allowed — the
caller then runs the real parse — but the plain-request corpus asserts it
does NOT happen for normal traffic, or the fast lane silently stops being
fast. The fuzz corpus runs regardless of the library; the native
assertions skip when native/libgiejsonscan.so is not built (test_native.py
pattern)."""

from __future__ import annotations

import json
import math
import random
import string

import pytest

from gie_tpu.extproc import fieldscan
from gie_tpu.extproc.fieldscan import FieldScan, scan, scan_native, scan_py

needs_native = pytest.mark.skipif(
    not fieldscan.available(), reason="native/libgiejsonscan.so not built"
)


def assert_parity(body: bytes, *, require_native: bool = False):
    """scan_native agrees with scan_py whenever it answers at all."""
    try:
        expected = scan_py(body)
    except OverflowError:
        # float(huge int) raises in Python — exactly what the legacy
        # _decode_tokens path does. The native scanner must fall back so
        # the fast lane crashes identically instead of silently clamping.
        assert scan_native(body) is None
        return None
    got = scan_native(body)
    if got is None:
        assert not require_native, f"unexpected fallback for {body[:80]!r}"
        return expected
    assert got == expected, (
        f"native/python divergence for {body[:120]!r}:\n"
        f"  native: {got}\n  python: {expected}"
    )
    # scan() must equal the same thing through either path.
    assert scan(body) == expected
    return expected


# --------------------------------------------------------------------------
# Directed corpus
# --------------------------------------------------------------------------


PLAIN_BODIES = [
    b'{"model": "llama-3.1-8b", "prompt": "hello", "max_tokens": 128}',
    b'{"model": "m", "messages": [{"role": "user", "content": "hi"}], '
    b'"max_completion_tokens": 512, "stream": true}',
    b'{"prompt": "x", "max_output_tokens": 9.5}',
    b'{"model": "", "stream": false}',
    b'{}',
    b'  {"model" : "spaced"}  ',
    b'{"temperature": 0.7, "top_p": 0.9, "n": 1, "logprobs": null}',
]


@needs_native
@pytest.mark.parametrize("body", PLAIN_BODIES)
def test_plain_requests_never_fall_back(body):
    assert_parity(body, require_native=True)


@needs_native
def test_extracted_fields_plain():
    fs = scan(b'{"model": "m1", "prompt": "p", "max_tokens": 64, '
              b'"stream": true, "messages": [1]}')
    assert fs.valid
    assert fs.model == "m1"
    assert fs.stream is True
    assert fs.prompt_is_str
    assert fs.messages_is_list
    assert fs.caps == (64.0, None, None)


UNICODE_BODIES = [
    '{"model": "ünïcodé-模型", "prompt": "日本語テキスト"}'.encode(),
    b'{"model": "esc\\u00e9\\u6a21"}',              # \u escapes
    b'{"model": "pair\\ud83d\\ude00end"}',          # surrogate pair
    b'{"model": "q\\"b\\\\s\\/f\\b\\f\\n\\r\\t"}',  # every simple escape
    '{"prompt": "' .encode() + "é".encode() * 700 + b'"}',  # long multibyte
    b'{"model": "\\ud83d\\ude00\\ud83d\\ude01"}',   # adjacent pairs
    '{"ключ": "значение", "model": "m"}'.encode(),  # non-ASCII keys
]


@needs_native
@pytest.mark.parametrize("body", UNICODE_BODIES)
def test_unicode_escapes(body):
    assert_parity(body, require_native=True)


FALLBACK_BODIES = [
    b'{"model": "\\ud800"}',            # lone high surrogate in model
    b'{"model": "\\udc00tail"}',        # lone low surrogate in model
    b'{"mod\\u0065l": "escaped-key"}',  # escaped top-level key
    b'\xef\xbb\xbf{"model": "bom"}',    # UTF-8 BOM (utf-8-sig decode)
    '{"model": "utf16"}'.encode("utf-16-le"),
    b'{"deep": ' + b'[' * 80 + b']' * 80 + b'}',   # past the depth cap
    b'{"max_tokens": ' + b'9' * 400 + b'}',        # float(int) overflow
    b'{"model": "' + b'm' * 8192 + b'"}',          # model beyond the cap
]


@needs_native
@pytest.mark.parametrize("body", FALLBACK_BODIES)
def test_hard_cases_fall_back_not_diverge(body):
    # These MAY fall back (and today all do); they must never disagree.
    assert_parity(body)


@needs_native
def test_cesu_surrogate_bytes_follow_surrogatepass():
    # json.loads(bytes) decodes with errors='surrogatepass': raw 3-byte
    # surrogate encodings are VALID (they become lone surrogates in the
    # str). Outside the model string that is just a valid document; in
    # the model string the scanner must fall back (lone-surrogate rule).
    assert_parity(b'{"a": "\xed\xa0\x80"}', require_native=True)
    assert_parity(b'{"prompt": "\xed\xb0\x80", "model": "ok"}',
                  require_native=True)
    assert_parity(b'{"model": "\xed\xa0\x80"}')  # fallback allowed


@needs_native
def test_lone_surrogate_outside_model_is_fine():
    # Python keeps lone surrogates in non-model strings; validity-wise the
    # document parses, and the scanner only needs Python semantics for the
    # model string itself.
    assert_parity(b'{"prompt": "\\ud800", "model": "ok"}',
                  require_native=True)


DUPLICATE_KEY_BODIES = [
    b'{"model": "first", "model": "last"}',
    b'{"model": "str", "model": 5}',            # type change: last wins
    b'{"model": 5, "model": "str"}',
    b'{"max_tokens": 1, "max_tokens": 2}',
    b'{"max_tokens": 7, "max_tokens": "nan"}',  # number -> non-number
    b'{"max_tokens": true, "max_tokens": 3}',
    b'{"stream": true, "stream": 0}',
    b'{"stream": 0, "stream": {"a": 1}}',
    b'{"prompt": "s", "prompt": [1]}',
    b'{"messages": [1], "messages": "no"}',
]


@needs_native
@pytest.mark.parametrize("body", DUPLICATE_KEY_BODIES)
def test_duplicate_keys_last_wins(body):
    assert_parity(body, require_native=True)


NUMBER_BODIES = [
    b'{"max_tokens": 0}',
    b'{"max_tokens": -1}',
    b'{"max_tokens": -0.0}',
    b'{"max_tokens": 1e400}',          # inf, like Python float("1e400")
    b'{"max_tokens": -1e400}',
    b'{"max_tokens": 1.5e-8}',
    b'{"max_tokens": 16, "max_completion_tokens": 32, '
    b'"max_output_tokens": 64}',
    b'{"max_tokens": NaN}',            # allow_nan default
    b'{"max_tokens": Infinity}',
    b'{"max_tokens": -Infinity}',
    b'{"a": NaN, "b": [Infinity, -Infinity]}',
    b'{"max_tokens": 123456789012345678901234567890}',  # big but floatable
    b'{"max_tokens": 1E+3}',
    b'{"max_tokens": 0.5}',
    b'{"stream": 0.0}',
    b'{"stream": -0.0}',
    b'{"stream": NaN}',                # NaN is truthy
]


@needs_native
@pytest.mark.parametrize("body", NUMBER_BODIES)
def test_number_semantics(body):
    assert_parity(body, require_native=True)


INVALID_BODIES = [
    b'',
    b'   ',
    b'not json',
    b'{"a": 1',                 # truncated object
    b'{"a": "unterminated',     # truncated string
    b'{"a": 1e}',               # bad exponent
    b'{"a": 01}',               # leading zero
    b'{"a": .5}',
    b'{"a": 1.}',
    b'{"a": +1}',
    b'{"a": -}',
    b'{"a": tru}',
    b'{"a": 1,}',               # trailing comma
    b'{,}',
    b'{"a" 1}',                 # missing colon
    b'{1: 2}',                  # non-string key
    b'{"a": 1} trailing',
    b'{"a": 1}{"b": 2}',
    b'{"a": "\x01"}',           # raw control char (strict mode)
    b'{"a": "\\x41"}',          # bad escape
    b'{"a": "\xff\xfe"}',       # invalid UTF-8 in string
    b'{"a": "\xc0\xaf"}',       # overlong encoding
    b'{"a": "\xf5\x80\x80\x80"}',  # > U+10FFFF
    b'[1, 2',                   # truncated array
    b'"just a string"',         # valid JSON, not an object
    b'42',
    b'null',
    b'true',
]


@needs_native
@pytest.mark.parametrize("body", INVALID_BODIES)
def test_invalid_and_non_object(body):
    assert_parity(body)


@needs_native
def test_nested_structures_do_not_leak_into_top_level():
    assert_parity(
        b'{"outer": {"model": "inner", "max_tokens": 999, "stream": true},'
        b' "list": [{"model": "deep"}, [1, [2, [3]]]],'
        b' "model": "top"}',
        require_native=True,
    )


@needs_native
def test_large_prompt_over_1mib():
    big = b'x' * (1024 * 1024 + 4096)
    body = (b'{"model": "big", "prompt": "' + big
            + b'", "max_tokens": 42, "stream": false}')
    fs = assert_parity(body, require_native=True)
    assert fs.valid and fs.model == "big" and fs.caps[0] == 42.0


@needs_native
def test_large_chat_messages():
    msgs = [{"role": "user", "content": "y" * 4096} for _ in range(64)]
    body = json.dumps({"model": "chat", "messages": msgs,
                       "max_completion_tokens": 256}).encode()
    fs = assert_parity(body, require_native=True)
    assert fs.messages_is_list and fs.caps == (None, 256.0, None)


@needs_native
def test_truncations_of_a_valid_body():
    body = json.dumps({
        "model": "mé\U0001F600", "prompt": "p" * 100,
        "max_tokens": 7, "stream": True, "messages": [{"a": [1, 2]}],
    }).encode()
    for cut in range(len(body)):
        assert_parity(body[:cut])


# --------------------------------------------------------------------------
# Randomized fuzz
# --------------------------------------------------------------------------


def _rand_value(rng: random.Random, depth: int):
    kind = rng.randrange(8 if depth < 3 else 6)
    if kind == 0:
        return rng.choice([None, True, False])
    if kind == 1:
        return rng.randrange(-(10 ** 6), 10 ** 6)
    if kind == 2:
        return rng.uniform(-1e6, 1e6)
    if kind == 3:
        n = rng.randrange(0, 20)
        return "".join(rng.choice(string.printable) for _ in range(n))
    if kind == 4:
        return "".join(
            chr(rng.choice([0x65, 0xE9, 0x4E2D, 0x1F600, 0x20AC]))
            for _ in range(rng.randrange(0, 6))
        )
    if kind == 5:
        return rng.choice([float("nan"), float("inf"), float("-inf"),
                           0.0, -0.0, 1e308, -1e308])
    if kind == 6:
        return [_rand_value(rng, depth + 1)
                for _ in range(rng.randrange(0, 4))]
    return {
        f"k{rng.randrange(6)}": _rand_value(rng, depth + 1)
        for _ in range(rng.randrange(0, 4))
    }


_WATCHED = ("model", "stream", "prompt", "messages", "max_tokens",
            "max_completion_tokens", "max_output_tokens")


@needs_native
@pytest.mark.parametrize("seed", range(12))
def test_fuzz_random_objects(seed):
    rng = random.Random(0xF1E1D + seed)
    for _ in range(150):
        obj = {}
        for _ in range(rng.randrange(0, 8)):
            key = (rng.choice(_WATCHED) if rng.random() < 0.6
                   else f"other{rng.randrange(4)}")
            obj[key] = _rand_value(rng, 0)
        body = json.dumps(obj, ensure_ascii=bool(rng.random() < 0.5)).encode()
        assert_parity(body, require_native=True)


@needs_native
@pytest.mark.parametrize("seed", range(6))
def test_fuzz_mutated_bytes(seed):
    """Random byte mutations of valid bodies: mostly invalid JSON — the
    scanner must classify them exactly like json.loads (and may never
    crash or diverge)."""
    rng = random.Random(0xBAD + seed)
    base = json.dumps({
        "model": "mut", "prompt": "p" * 40, "max_tokens": 9,
        "stream": False, "messages": [{"role": "user", "content": "c"}],
    }).encode()
    for _ in range(200):
        b = bytearray(base)
        for _ in range(rng.randrange(1, 4)):
            op = rng.randrange(3)
            pos = rng.randrange(len(b))
            if op == 0:
                b[pos] = rng.randrange(256)
            elif op == 1:
                del b[pos]
            else:
                b.insert(pos, rng.randrange(256))
        assert_parity(bytes(b))


# --------------------------------------------------------------------------
# Pure-Python reference semantics (run even without the library)
# --------------------------------------------------------------------------


def test_scan_py_matches_parse_body_validity():
    from gie_tpu.bbr.chain import parse_body

    for body in (PLAIN_BODIES + INVALID_BODIES
                 + [b'[1]', b'"s"', b'{"model": "m"}']):
        assert scan_py(body).valid == (parse_body(body) is not None)


def test_scan_py_field_rules():
    fs = scan_py(b'{"model": 5, "stream": "s", "prompt": 1, '
                 b'"messages": {}, "max_tokens": true}')
    assert fs.valid
    assert fs.model is None          # non-string model
    assert fs.stream is True         # bool("s")
    assert not fs.prompt_is_str
    assert not fs.messages_is_list
    assert fs.caps == (None, None, None)   # bool is not a number


def test_fieldscan_equality_handles_nan():
    a = FieldScan(True, caps=(float("nan"), None, None))
    b = FieldScan(True, caps=(float("nan"), None, None))
    c = FieldScan(True, caps=(1.0, None, None))
    assert a == b and a != c


@needs_native
def test_scan_falls_back_to_python_transparently():
    # A fallback-class input still yields a correct FieldScan via scan().
    body = b'{"mod\\u0065l": "escaped"}'
    assert scan_native(body) is None
    assert scan(body) == scan_py(body)
    assert scan(body).model == "escaped"
