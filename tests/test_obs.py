"""gie-obs (ISSUE 9, docs/OBSERVABILITY.md): trace propagation +
sampling determinism, the flight recorder's lock-free ring, trace
closure on every exit path, the /debugz plane, exemplar exposition, and
the metrics-catalog lint."""

from __future__ import annotations

import json
import threading
import time
import urllib.request

import numpy as np
import pytest

from gie_tpu import obs
from gie_tpu.datastore import Datastore
from gie_tpu.datastore.objects import EndpointPool, Pod
from gie_tpu.extproc import metadata as mdkeys
from gie_tpu.extproc.server import (
    ExtProcError,
    RoundRobinPicker,
    ShedError,
    StreamingServer,
)
from gie_tpu.metricsio import MetricsStore
from gie_tpu.obs.debugz import DebugzServer
from gie_tpu.obs.recorder import FlightRecorder
from gie_tpu.obs.trace import Sampler, Tracer, trace_id_from_headers
from gie_tpu.resilience.deadline import DeadlineExceeded
from gie_tpu.runtime import metrics as own_metrics
from gie_tpu.sched import ProfileConfig, Scheduler
from gie_tpu.sched.batching import BatchingTPUPicker

from tests.test_dataplane import _resp_headers_msg, _server
from tests.test_extproc import FakeStream, headers_msg, make_ds

TRACEPARENT = "00-" + "ab" * 16 + "-" + "cd" * 8 + "-01"
TID = "ab" * 16


@pytest.fixture(autouse=True)
def _clean_obs():
    obs.uninstall()
    yield
    obs.uninstall()


# --------------------------------------------------------------------------
# Sampler determinism
# --------------------------------------------------------------------------


def test_sampler_bit_identical_per_trace_id():
    """Same (seed, rate) -> the SAME keep/drop verdict for every trace
    ID, across instances — the fleet-wide consistency claim."""
    ids = [f"{i:032x}" for i in range(2000)]
    a = Sampler(0.25, seed=7)
    b = Sampler(0.25, seed=7)
    va = [a.keep(t) for t in ids]
    vb = [b.keep(t) for t in ids]
    assert va == vb
    # Replaying one ID never changes its verdict (stateless).
    assert all(a.keep(ids[3]) == va[3] for _ in range(10))
    # A different seed samples a different subset.
    assert [Sampler(0.25, seed=8).keep(t) for t in ids] != va
    # Rate edges and the achieved fraction.
    assert not any(Sampler(0.0, seed=7).keep(t) for t in ids)
    assert all(Sampler(1.0, seed=7).keep(t) for t in ids)
    frac = sum(va) / len(va)
    assert 0.15 < frac < 0.35


def test_trace_id_extraction_precedence():
    tid, rid = trace_id_from_headers({
        "traceparent": [TRACEPARENT],
        "x-request-id": ["9f1d4c3a-77aa-43f2-a1b0-2f8e6f1d9c55"],
    })
    assert tid == TID
    assert rid == "9f1d4c3a-77aa-43f2-a1b0-2f8e6f1d9c55"
    # x-request-id fallback: UUID hex with dashes stripped.
    tid, _ = trace_id_from_headers(
        {"x-request-id": ["9f1d4c3a-77aa-43f2-a1b0-2f8e6f1d9c55"]})
    assert tid == "9f1d4c3a77aa43f2a1b02f8e6f1d9c55"
    # Non-hex request IDs hash to a stable 32-hex ID.
    t1, _ = trace_id_from_headers({"x-request-id": ["req-XYZ"]})
    t2, _ = trace_id_from_headers({"x-request-id": ["req-XYZ"]})
    assert t1 == t2 and len(t1) == 32
    # Malformed traceparent falls through to x-request-id.
    tid, _ = trace_id_from_headers({
        "traceparent": ["garbage"], "x-request-id": ["abcd" * 8]})
    assert tid == "abcd" * 8
    # Nothing usable -> empty (the tracer generates).
    assert trace_id_from_headers({}) == ("", "")
    tracer = Tracer(1.0)
    ctx = tracer.begin({})
    assert len(ctx.trace_id) == 32 and ctx.trace_id != "0" * 32


# --------------------------------------------------------------------------
# Flight-recorder ring
# --------------------------------------------------------------------------


def test_ring_wraparound_under_concurrent_writers():
    """8 writers x 300 records into a 64-slot ring: never more than 64
    live records, every survivor intact and from the newest window, no
    torn/half-written entries."""
    rec = FlightRecorder(size=64)
    n_threads, per = 8, 300
    total = n_threads * per

    def writer(k: int):
        for i in range(per):
            rec.append({"writer": k, "i": i, "payload": "x" * 32})

    threads = [threading.Thread(target=writer, args=(k,))
               for k in range(n_threads)]
    [t.start() for t in threads]
    [t.join() for t in threads]
    snap = rec.snapshot()
    assert len(snap) == 64
    seqs = [r["seq"] for r in snap]
    assert seqs == sorted(seqs) and len(set(seqs)) == 64
    assert max(seqs) == total - 1
    # Only the newest window survives wraparound.
    assert min(seqs) >= total - 64 - n_threads
    for r in snap:
        assert r["payload"] == "x" * 32 and 0 <= r["writer"] < n_threads
    # Export is valid JSON of the same records.
    assert len(json.loads(rec.export_json())) == 64
    # find() by seq.
    assert rec.find(seq=max(seqs))["seq"] == max(seqs)


def test_ring_trims_newest_first():
    rec = FlightRecorder(size=8)
    for i in range(20):
        rec.append({"i": i})
    top = rec.snapshot(n=3)
    assert [r["seq"] for r in top] == [19, 18, 17]


# --------------------------------------------------------------------------
# Trace closure on every exit path
# --------------------------------------------------------------------------


class _RaisingPicker:
    def __init__(self, exc):
        self.exc = exc

    def pick(self, req, candidates):
        raise self.exc


class _AbortStream(FakeStream):
    def recv(self):
        from gie_tpu.extproc.server import StreamAborted

        if self.messages:
            return super().recv()
        raise StreamAborted()


def _outcomes(tracer: Tracer) -> dict:
    return {t["trace_id"]: t["outcome"] for t in tracer.traces("recent", 99)}


def test_trace_closes_on_every_exit_path():
    tracer = Tracer(1.0, slow_s=10.0)
    obs.install(tracer=tracer)
    ds = make_ds()
    hdrs = {"traceparent": TRACEPARENT, "content-type": "application/json"}

    # ok: pick + response headers.
    srv = StreamingServer(ds, RoundRobinPicker())
    srv.process(FakeStream([headers_msg(hdrs),
                            _resp_headers_msg(served="10.0.0.1:8000")]))
    # shed -> 429.
    StreamingServer(ds, _RaisingPicker(ShedError())).process(
        FakeStream([headers_msg(hdrs)]))
    # deadline -> 503.
    StreamingServer(ds, _RaisingPicker(DeadlineExceeded("queue"))).process(
        FakeStream([headers_msg(hdrs)]))
    # unavailable -> stream-fatal UNAVAILABLE.
    import grpc

    with pytest.raises(ExtProcError):
        StreamingServer(ds, _RaisingPicker(ExtProcError(
            grpc.StatusCode.UNAVAILABLE, "no endpoints"))).process(
            FakeStream([headers_msg(hdrs)]))
    # abort after pick, before response headers.
    srv2 = StreamingServer(ds, RoundRobinPicker())
    srv2.process(_AbortStream([headers_msg(hdrs)]))

    outs = [t["outcome"] for t in tracer.traces("recent", 99)]
    for expected in ("ok", "shed", "deadline", "unavailable", "aborted"):
        assert expected in outs, f"{expected} missing from {outs}"
    assert tracer.exported_total == 5
    # Error-class traces also land in the errors feed; ok does not.
    err_outs = {t["outcome"] for t in tracer.traces("errors", 99)}
    assert err_outs == {"shed", "deadline", "unavailable", "aborted"}
    # Every trace carries the propagated W3C trace ID and staged events.
    for t in tracer.traces("recent", 99):
        assert t["trace_id"] == TID
        assert t["events"][0]["stage"] == "admission"


def test_errors_export_even_when_unsampled():
    """The always-sample classes: with head sampling effectively off for
    this trace ID, an ok request exports nothing but a shed exports."""
    tracer = Tracer(1e-9, seed=0, slow_s=10.0)  # keeps ~nothing
    assert not tracer.sampler.keep(TID)
    obs.install(tracer=tracer)
    ds = make_ds()
    hdrs = {"traceparent": TRACEPARENT}
    StreamingServer(ds, RoundRobinPicker()).process(
        FakeStream([headers_msg(hdrs),
                    _resp_headers_msg(served="10.0.0.1:8000")]))
    assert tracer.exported_total == 0
    StreamingServer(ds, _RaisingPicker(ShedError())).process(
        FakeStream([headers_msg(hdrs)]))
    assert tracer.exported_total == 1
    assert tracer.traces("errors", 9)[0]["outcome"] == "shed"


def test_slow_trace_exports_as_tail_outlier():
    tracer = Tracer(1e-9, slow_s=0.0)  # everything is an outlier
    obs.install(tracer=tracer)
    StreamingServer(make_ds(), RoundRobinPicker()).process(
        FakeStream([headers_msg({"traceparent": TRACEPARENT})]))
    assert [t["outcome"] for t in tracer.traces("slow", 9)] == ["ok"]


def test_get_finds_slow_trace_after_recent_eviction():
    """A tail-outlier trace stays findable by ID even after newer
    exports evict it from the recent feed (it lives on in _slow)."""
    from gie_tpu.obs.trace import TraceCtx

    tracer = Tracer(1.0, slow_s=1.0, keep=2)
    now = time.monotonic()
    slow_ctx = TraceCtx("aa" * 16, "", True, now - 5.0)  # 5 s latency
    tracer.finish(slow_ctx, "ok")
    for i in range(2):  # evict it from _recent (maxlen 2)
        tracer.finish(TraceCtx(f"{i:032x}", "", True, now), "ok")
    assert all(t["trace_id"] != "aa" * 16
               for t in tracer.traces("recent", 9))
    found = tracer.get("aa" * 16)
    assert found is not None and found["latency_ms"] >= 5000


# --------------------------------------------------------------------------
# End-to-end: records through the real batching picker
# --------------------------------------------------------------------------

POOL = EndpointPool(selector={"app": "x"}, target_ports=[8000],
                    namespace="default")


def _stack(n_pods=4):
    sched = Scheduler(ProfileConfig(load_decay=1.0))
    ms = MetricsStore()
    ds = Datastore(on_slot_reclaimed=lambda s: (sched.evict_endpoint(s),
                                                ms.remove(s)))
    ds.pool_set(POOL)
    for i in range(n_pods):
        ds.pod_update_or_add(Pod(name=f"p{i}", labels={"app": "x"},
                                 ip=f"10.7.0.{i + 1}"))
    picker = BatchingTPUPicker(sched, ds, ms, max_wait_s=0.002)
    return sched, ds, ms, picker


class _EchoStream(FakeStream):
    """Request headers, then response headers echoing the picked primary
    as served with a 200 (tests/test_scenarios.py EchoStream shape)."""

    def recv(self):
        if not self.messages and len(self.sent) == 1:
            mut = self.sent[0].request_headers.response.header_mutation
            dest = next(
                o.header.raw_value.decode() for o in mut.set_headers
                if o.header.key == mdkeys.DESTINATION_ENDPOINT_KEY)
            self.messages.append(
                _resp_headers_msg(served=dest.split(",")[0]))
        return super().recv()


def test_full_pick_record_explains_the_decision():
    tracer = Tracer(1.0, slow_s=10.0)
    recorder = FlightRecorder(64)
    obs.install(tracer=tracer, recorder=recorder)
    sched, ds, ms, picker = _stack()
    srv = _server(ds, picker)
    try:
        stream = _EchoStream([headers_msg({"traceparent": TRACEPARENT})])
        srv.process(stream)
        recs = recorder.snapshot()
        assert len(recs) == 1
        rec = recs[0]
        # The acceptance shape: chosen endpoint, scorer breakdown, rung,
        # serve outcome — all in one record, joined to the trace.
        assert rec["trace_id"] == TID
        assert rec["rung"] == "full"
        assert rec["chosen"].startswith("10.7.0.")
        assert rec["chosen_slot"] in rec["candidates"]
        assert len(rec["candidates"]) == 4
        assert set(rec["scorers"]) >= {"queue", "kv_cache"}
        assert all(0.0 <= v <= 1.0 for v in rec["scorers"].values())
        assert rec["ranked"] and rec["ranked"][0]["slot"] == rec["chosen_slot"]
        assert rec["outcome"] == "2xx"
        assert rec["served"] == rec["chosen"]
        assert rec["fallback_rank"] == 0
        assert rec["excluded_breaker"] == [] and rec["excluded_drain"] == []
        # The exported trace carries the pick summary + queue/pick events.
        tr = tracer.get(TID)
        assert tr is not None and tr["pick"]["chosen"] == rec["chosen"]
        stages = [e["stage"] for e in tr["events"]]
        assert stages[:1] == ["admission"]
        assert "queued" in stages and "picked" in stages
        assert "response_headers" in stages
    finally:
        picker.close()


def test_drain_exclusion_recorded():
    """A pick whose candidate list still contains a draining endpoint
    records the wave-level exclusion (the rolling-upgrade audit)."""
    from gie_tpu.extproc.server import PickRequest

    recorder = FlightRecorder(64)
    obs.install(recorder=recorder)
    sched, ds, ms, picker = _stack()
    try:
        assert ds.pod_mark_draining("default", "p0")
        drained_slot = next(
            ep.slot for ep in ds.endpoints() if ep.pod_name == "p0")
        # Candidates deliberately include the draining endpoint: the
        # WAVE filter (not admission candidacy) must exclude it.
        res = picker.pick(PickRequest(headers={}, body=b"x"),
                          ds.endpoints())
        rec = recorder.snapshot()[-1]
        assert drained_slot in rec["excluded_drain"]
        assert drained_slot in rec["draining"]
        assert rec["chosen_slot"] != drained_slot
        assert res.endpoint != "10.7.0.1:8000"  # p0 is draining
    finally:
        picker.close()


def test_degraded_pick_records_rung():
    from gie_tpu.extproc.server import PickRequest
    from gie_tpu.obs.trace import TraceCtx
    from gie_tpu.resilience.ladder import (
        DegradationLadder, LadderConfig, ResilienceState, Rung)

    recorder = FlightRecorder(64)
    obs.install(recorder=recorder)
    rs = ResilienceState(ladder=DegradationLadder(
        LadderConfig(dispatch_error_streak=1, probe_interval_s=3600.0)))
    sched, ds, ms, _ = _stack()
    picker = BatchingTPUPicker(sched, ds, ms, max_wait_s=0.002,
                               resilience=rs)
    try:
        rs.ladder.note_dispatch_error()          # -> CACHED
        assert rs.ladder.rung() == Rung.CACHED
        rs.ladder.should_probe()                 # consume the first probe
        tr = TraceCtx(TID, "", True, time.monotonic())
        res = picker.pick(PickRequest(headers={}, body=b"x", trace=tr),
                          ds.pick_candidates())
        assert res.endpoint
        recs = [r for r in recorder.snapshot() if r["rung"] == "cached"]
        assert recs, "degraded pick published no record"
        rec = recs[-1]
        assert rec["chosen"] == res.endpoint
        assert rec["trace_id"] == TID
        assert "degraded_cached" in rec["scorers"]
        assert rec["outcome"] == "picked"
        # Degraded picks keep the full trace lifecycle: the "picked"
        # stage still lands even when the device path was skipped.
        assert "picked" in [name for name, _ in tr.events]
    finally:
        picker.close()


# --------------------------------------------------------------------------
# /debugz plane + exemplars
# --------------------------------------------------------------------------


def _get(port, path, accept=None):
    req = urllib.request.Request(f"http://127.0.0.1:{port}{path}")
    if accept:
        req.add_header("Accept", accept)
    with urllib.request.urlopen(req, timeout=5) as resp:
        return resp.status, resp.headers.get("Content-Type"), resp.read()


def test_debugz_server_zpages_and_metrics():
    srv = DebugzServer(0, own_metrics.REGISTRY, {
        "ping": lambda q: {"ok": True, "n": q.get("n")},
        "np": lambda q: {"v": np.float32(1.5)},  # numpy must serialize
    }, bind="127.0.0.1")
    try:
        status, ctype, body = _get(srv.port, "/debugz")
        assert status == 200 and "json" in ctype
        catalog = json.loads(body)
        assert "/debugz/ping" in catalog["pages"]
        status, _, body = _get(srv.port, "/debugz/ping?n=3")
        assert json.loads(body) == {"ok": True, "n": "3"}
        assert json.loads(_get(srv.port, "/debugz/np")[2])["v"] == 1.5
        # Prometheus text by default...
        status, ctype, body = _get(srv.port, "/metrics")
        assert status == 200 and b"gie_picks_total" in body
        # ...OpenMetrics under negotiation (the exemplar transport).
        own_metrics.PICK_LATENCY.observe(
            0.012, {"trace_id": "feed" * 8})
        status, ctype, body = _get(
            srv.port, "/metrics",
            accept="application/openmetrics-text; version=1.0.0")
        assert "openmetrics" in ctype
        assert body.rstrip().endswith(b"# EOF")
        assert b'# {trace_id="' + b"feed" * 8 + b'"}' in body
        # Unknown zpages 404 without killing the server.
        assert _get(srv.port, "/debugz/ping")[0] == 200
        with pytest.raises(urllib.error.HTTPError):
            _get(srv.port, "/debugz/nope")
        # prometheus_client handler parity: exposition on any
        # non-/debugz path, name[] filtering, gzip negotiation.
        assert b"gie_picks_total" in _get(srv.port, "/")[2]
        filtered = _get(srv.port, "/metrics?name[]=gie_active_streams")[2]
        assert b"gie_active_streams" in filtered
        assert b"gie_picks_total" not in filtered
        import gzip as _gzip

        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/metrics")
        req.add_header("Accept-Encoding", "gzip")
        with urllib.request.urlopen(req, timeout=5) as resp:
            assert resp.headers.get("Content-Encoding") == "gzip"
            assert b"gie_picks_total" in _gzip.decompress(resp.read())
    finally:
        srv.close()


def test_debugz_bind_hardening_loopback_only_by_default():
    """ISSUE 10 satellite (docs/OBSERVABILITY.md "bind hardening"): the
    /debugz zpages answer loopback peers only unless --debugz-bind names
    a non-loopback address; /metrics is unaffected either way."""
    srv = DebugzServer(0, own_metrics.REGISTRY,
                       {"ping": lambda q: {"ok": True}}, bind="127.0.0.1")
    try:
        # Default: loopback-only. The peer-gate predicate is the unit
        # under test (a non-loopback client cannot be faked over lo).
        assert srv._debugz_allowed("127.0.0.1")
        assert srv._debugz_allowed("::1")
        assert not srv._debugz_allowed("10.0.0.5")
        assert not srv._debugz_allowed("192.168.1.9")
        assert not srv._debugz_allowed("not-an-ip")   # closed by default
        # Integration: a loopback client reads zpages AND metrics.
        assert _get(srv.port, "/debugz/ping")[0] == 200
        assert _get(srv.port, "/metrics")[0] == 200
    finally:
        srv.close()
    # Explicit opt-out: a non-loopback --debugz-bind opens the gate.
    opened = DebugzServer(0, own_metrics.REGISTRY, {},
                          bind="127.0.0.1", debugz_bind="0.0.0.0")
    try:
        assert opened._debugz_allowed("10.0.0.5")
        assert opened._debugz_allowed("127.0.0.1")
    finally:
        opened.close()
    # Loopback ALIASES and unparsable values stay gated — only an
    # explicit non-loopback ADDRESS opts out (a typo must not silently
    # disable the hardening).
    for bind in ("127.0.0.2", " 127.0.0.1 ", "Localhost", "wat"):
        aliased = DebugzServer(0, own_metrics.REGISTRY, {},
                               bind="127.0.0.1", debugz_bind=bind)
        try:
            assert not aliased._debugz_allowed("10.0.0.5"), bind
        finally:
            aliased.close()


def test_debugz_gate_returns_403_not_404(monkeypatch):
    """A blocked peer gets 403 on every /debugz path — including ones
    that exist — so the gate does not leak the zpage catalog shape."""
    srv = DebugzServer(0, own_metrics.REGISTRY,
                       {"ping": lambda q: {"ok": True}}, bind="127.0.0.1")
    try:
        monkeypatch.setattr(
            srv, "_debugz_allowed", lambda peer: False)
        for path in ("/debugz", "/debugz/ping", "/debugz/nope"):
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get(srv.port, path)
            assert ei.value.code == 403
        # The exposition stays reachable for Prometheus regardless.
        assert _get(srv.port, "/metrics")[0] == 200
    finally:
        srv.close()


def test_debugz_token_authenticates_remote_peers(monkeypatch):
    """ISSUE 11 satellite (docs/OBSERVABILITY.md "bind hardening"):
    with --debugz-token set, a NON-loopback peer must present the
    bearer token on /debugz paths — 401 without it or with a wrong one,
    200 with it — while loopback access needs no token and /metrics is
    untouched either way."""
    import urllib.error

    srv = DebugzServer(0, own_metrics.REGISTRY,
                       {"ping": lambda q: {"ok": True}},
                       bind="127.0.0.1", debugz_token="s3cret-tok")
    try:
        # Loopback peer: no token needed (unchanged default).
        assert _get(srv.port, "/debugz/ping")[0] == 200
        # Simulate a remote peer (a non-loopback client cannot be faked
        # over lo; the peer predicate is the seam, same as the bind
        # tests above).
        monkeypatch.setattr(srv, "_peer_is_loopback", lambda peer: False)
        for headers in ({}, {"Authorization": "Bearer wrong"},
                        {"Authorization": "Basic s3cret-tok"}):
            req = urllib.request.Request(
                f"http://127.0.0.1:{srv.port}/debugz/ping", headers=headers)
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(req, timeout=5)
            assert ei.value.code == 401, headers
        good = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/debugz/ping",
            headers={"Authorization": "Bearer s3cret-tok"})
        with urllib.request.urlopen(good, timeout=5) as resp:
            assert resp.status == 200
        # The exposition never needs the token.
        assert _get(srv.port, "/metrics")[0] == 200
    finally:
        srv.close()


def test_debugz_token_overrides_bind_opt_out(monkeypatch):
    """A non-loopback --debugz-bind normally opens the gate; with a
    token configured the token still gates remote peers — exposing
    /debugz off-loopback WITH auth is the feature."""
    import urllib.error

    srv = DebugzServer(0, own_metrics.REGISTRY,
                       {"ping": lambda q: {"ok": True}},
                       bind="127.0.0.1", debugz_bind="0.0.0.0",
                       debugz_token="tok")
    try:
        monkeypatch.setattr(srv, "_peer_is_loopback", lambda peer: False)
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(srv.port, "/debugz/ping")
        assert ei.value.code == 401
    finally:
        srv.close()


def test_tenant_sample_rate_overrides_fleet_rate():
    """--obs-tenant-sample (ISSUE 11): a mapped tenant's rate replaces
    the fleet head-sampling decision — 1.0 always keeps, 0.0 always
    drops — deterministically per trace ID, while unmapped tenants keep
    the fleet verdict."""
    tracer = Tracer(0.0, seed=5,
                    tenant_rates={"noisy": 1.0, "spammy": 0.0})
    ids = [f"{i:032x}" for i in range(50)]

    def begin(tid, tenant=None):
        headers = {"traceparent": [f"00-{tid}-" + "cd" * 8 + "-01"]}
        if tenant is not None:
            headers[mdkeys.FLOW_FAIRNESS_ID_KEY] = [tenant]
        return tracer.begin(headers)

    # Fleet rate 0: unmapped traffic never head-samples.
    assert not any(begin(t).sampled for t in ids)
    assert not any(begin(t, "unmapped").sampled for t in ids)
    # The noisy tenant samples at 1.0; the spammy one never.
    assert all(begin(t, "noisy").sampled for t in ids)
    assert not any(begin(t, "spammy").sampled for t in ids)
    # Fractional override is deterministic per trace ID.
    frac_tracer = Tracer(0.0, seed=5, tenant_rates={"some": 0.5})
    v1 = [frac_tracer.begin({
        "traceparent": [f"00-{t}-" + "cd" * 8 + "-01"],
        mdkeys.FLOW_FAIRNESS_ID_KEY: ["some"]}).sampled for t in ids]
    v2 = [frac_tracer.begin({
        "traceparent": [f"00-{t}-" + "cd" * 8 + "-01"],
        mdkeys.FLOW_FAIRNESS_ID_KEY: ["some"]}).sampled for t in ids]
    assert v1 == v2 and any(v1) and not all(v1)
    with pytest.raises(ValueError, match="tenant sample rate"):
        Tracer(0.0, tenant_rates={"bad": 1.5})


def test_serve_latency_exemplar_links_bucket_to_trace():
    """ISSUE 11 satellite: the serve-outcome hop attaches a trace-ID
    exemplar to gie_serve_latency_seconds for head-sampled requests,
    mirroring the admission/pick exemplar wiring."""
    from gie_tpu.obs.trace import TraceCtx

    sched, ds, ms, picker = _stack(n_pods=2)
    try:
        tr = TraceCtx("fe" * 16, "", sampled=True, started=time.monotonic())
        picker._note_serve_outcome("10.9.0.1:8000", ok=True, cls="2xx",
                                   latency_s=0.033, trace=tr)
        # Unsampled and trace-less observations stay exemplar-free.
        un = TraceCtx("ad" * 16, "", sampled=False, started=time.monotonic())
        picker._note_serve_outcome("10.9.0.1:8000", ok=True, cls="2xx",
                                   latency_s=0.040, trace=un)
        picker._note_serve_outcome("10.9.0.1:8000", ok=True, cls="2xx",
                                   latency_s=0.050)
    finally:
        picker.close()
    from prometheus_client.openmetrics.exposition import generate_latest

    text = generate_latest(own_metrics.REGISTRY).decode()
    line = next(
        (ln for ln in text.splitlines()
         if ln.startswith("gie_serve_latency_seconds_bucket")
         and f'trace_id="{"fe" * 16}"' in ln), None)
    assert line is not None, "serve bucket carries no trace exemplar"
    assert f'trace_id="{"ad" * 16}"' not in text


def test_admission_exemplar_links_bucket_to_trace():
    tracer = Tracer(1.0, slow_s=10.0)
    obs.install(tracer=tracer)
    StreamingServer(make_ds(), RoundRobinPicker()).process(
        FakeStream([headers_msg({"traceparent": TRACEPARENT})]))
    from prometheus_client.openmetrics.exposition import generate_latest

    text = generate_latest(own_metrics.REGISTRY).decode()
    line = next(
        (ln for ln in text.splitlines()
         if ln.startswith("gie_extproc_admission_seconds_bucket")
         and f'trace_id="{TID}"' in ln), None)
    assert line is not None, "admission bucket carries no trace exemplar"


# --------------------------------------------------------------------------
# Satellites: catalog lint, build info, artifact dump, accessors, zpages
# --------------------------------------------------------------------------


def test_obs_check_clean_on_real_catalog():
    from gie_tpu.obs.metricscheck import check_registry

    own_metrics.register_pool_aggregates(lambda: {})
    assert check_registry(own_metrics.REGISTRY) == []


def test_obs_check_catches_bad_metrics():
    import prometheus_client as prom

    from gie_tpu.obs.metricscheck import check_registry

    reg = prom.CollectorRegistry()
    prom.Counter("wrong_prefix_total", "has help", registry=reg)
    prom.Gauge("gie_no_help", "", registry=reg)
    prom.Gauge("gie_cardinality", "per-endpoint series", ["endpoint"],
               registry=reg)
    prom.Counter("gie_wide_total", "too many labels",
                 ["a", "b", "c", "d", "e"], registry=reg)
    findings = "\n".join(check_registry(reg))
    assert "OC001 wrong_prefix" in findings
    assert "OC002 gie_no_help" in findings
    assert "OC003 gie_wide" in findings
    assert "OC004 gie_cardinality" in findings


def test_build_info_gauge():
    own_metrics.set_build_info(fast_lane=True, resilience=True, obs=False,
                               wire=True, workers=2)
    from gie_tpu.version import __version__

    assert own_metrics.REGISTRY.get_sample_value("gie_build_info", {
        "version": __version__, "fast_lane": "true",
        "resilience": "true", "obs": "false",
        "wire": "true", "workers": "2"}) == 1.0


def test_logging_trace_enabled_accessor():
    from gie_tpu.runtime import logging as own_logging

    own_logging.set_verbosity(2)
    assert not own_logging.trace_enabled()
    own_logging.set_verbosity(5)
    assert own_logging.trace_enabled()
    own_logging.set_verbosity(2)
    assert not own_logging.trace_enabled()


def test_dump_artifact_roundtrip(tmp_path):
    recorder = FlightRecorder(16)
    tracer = Tracer(1.0)
    obs.install(tracer=tracer, recorder=recorder)
    recorder.append({"trace_id": "t1", "chosen": "10.0.0.1:8000"})
    path = obs.dump_artifact(str(tmp_path), name="rolling upgrade/x")
    assert path is not None and "/" not in path[len(str(tmp_path)) + 1:]
    with open(path) as f:
        payload = json.load(f)
    assert payload["records"][0]["chosen"] == "10.0.0.1:8000"
    assert "traces" in payload
    obs.uninstall()
    assert obs.dump_artifact(str(tmp_path), name="nothing") is None


def test_zpage_report_shapes():
    """The provider surfaces the runner wires into /debugz: breaker
    board, scheduler, datastore, flow queue."""
    from gie_tpu.resilience.breaker import BreakerBoard

    board = BreakerBoard()
    for _ in range(6):
        board.record(3, ok=False)
    rep = board.report()
    assert rep["has_open"] and rep["breakers"]["3"]["state"] == "open"
    assert rep["breakers"]["3"]["opened_by"] == "scrape"

    sched, ds, ms, picker = _stack(n_pods=2)
    try:
        srep = sched.debug_report()
        assert srep["picker"] == "topk" and "queue" in srep["weights"]
        drep = ds.debug_report()
        assert drep["pool_synced"] and len(drep["endpoints"]) == 2
        assert drep["pool_generation"] >= 1
        qrep = picker.queue_report()
        assert qrep["depth"] == 0 and "pipeline_depth_limit" in qrep
    finally:
        picker.close()


def test_pick_result_record_updates_on_abort():
    """A stream that aborts after its pick closes the record as reset."""
    recorder = FlightRecorder(16)
    obs.install(recorder=recorder)
    sched, ds, ms, picker = _stack()
    srv = _server(ds, picker)
    try:
        srv.process(_AbortStream([headers_msg({})]))
        rec = recorder.snapshot()[-1]
        assert rec["outcome"] == "reset"
    finally:
        picker.close()


# --------------------------------------------------------------------------
# OTLP span export (ISSUE 12 satellite, docs/OBSERVABILITY.md "OTLP
# export"): trace dicts -> OTLP/HTTP JSON spans, batched off the hot
# path, federation hops as child spans — one joined trace per
# cross-cluster pick.
# --------------------------------------------------------------------------


def _trace_dict(trace_id="ab" * 16, outcome="ok", events=None):
    return {
        "trace_id": trace_id,
        "request_id": "rid-1",
        "sampled": True,
        "outcome": outcome,
        "latency_ms": 12.5,
        "finished_at": 1700000000.0,
        "events": events if events is not None else [
            {"stage": "admission", "at_ms": 0.0},
            {"stage": "picked", "at_ms": 3.0},
        ],
        "pick": {"chosen": "10.0.0.1:8000", "rung": "full",
                 "outcome": "picked"},
    }


def test_otlp_span_mapping_root_and_events():
    from gie_tpu.obs.otlp import trace_to_spans

    spans = trace_to_spans(_trace_dict())
    assert len(spans) == 1
    root = spans[0]
    assert root["traceId"] == "ab" * 16
    assert len(root["spanId"]) == 16
    assert root["name"] == "gie.request"
    assert [e["name"] for e in root["events"]] == ["admission", "picked"]
    assert int(root["endTimeUnixNano"]) > int(root["startTimeUnixNano"])
    assert root["status"]["code"] == 1
    # Error-class outcomes map to STATUS_CODE_ERROR.
    bad = trace_to_spans(_trace_dict(outcome="serve_5xx"))[0]
    assert bad["status"]["code"] == 2
    # Deterministic span IDs: replays and replicas agree.
    again = trace_to_spans(_trace_dict())[0]
    assert again["spanId"] == root["spanId"]


def test_otlp_federation_hop_is_a_child_span():
    from gie_tpu.obs.otlp import trace_to_spans

    spans = trace_to_spans(_trace_dict(events=[
        {"stage": "admission", "at_ms": 0.0},
        {"stage": "federation:west", "at_ms": 2.0},
        {"stage": "picked", "at_ms": 3.0},
    ]))
    assert len(spans) == 2
    root, hop = spans
    assert hop["name"] == "gie.federation"
    assert hop["parentSpanId"] == root["spanId"]
    assert hop["traceId"] == root["traceId"]
    assert {"key": "gie.peer_cluster",
            "value": {"stringValue": "west"}} in hop["attributes"]


def test_otlp_exporter_batches_to_http_sink():
    """The wired path: Tracer.on_export -> exporter queue -> background
    batch POST to a real local HTTP collector sink."""
    import http.server

    from gie_tpu.obs.otlp import OtlpSpanExporter
    from gie_tpu.obs.trace import Tracer

    bodies = []
    got = threading.Event()

    class Sink(http.server.BaseHTTPRequestHandler):
        def do_POST(self):
            n = int(self.headers.get("Content-Length", 0) or 0)
            bodies.append(json.loads(self.rfile.read(n)))
            got.set()
            self.send_response(200)
            self.send_header("Content-Length", "0")
            self.end_headers()

        def log_message(self, *a):
            pass

    httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Sink)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    exporter = OtlpSpanExporter(
        f"http://127.0.0.1:{httpd.server_address[1]}",
        flush_interval_s=0.05)
    tracer = Tracer(1.0)
    tracer.on_export = exporter.export
    try:
        ctx = tracer.begin({})
        ctx.event("federation:west")
        tracer.finish(ctx, "ok")
        assert got.wait(5.0), "sink never received a batch"
        payload = bodies[0]
        spans = payload["resourceSpans"][0]["scopeSpans"][0]["spans"]
        names = {s["name"] for s in spans}
        assert names == {"gie.request", "gie.federation"}
        res_attrs = payload["resourceSpans"][0]["resource"]["attributes"]
        assert {"key": "service.name",
                "value": {"stringValue": "gie-tpu-epp"}} in res_attrs
        deadline = time.monotonic() + 3.0
        while time.monotonic() < deadline and exporter.exported < 2:
            time.sleep(0.02)  # the POST finishes after the sink flags
        assert exporter.exported == 2 and exporter.post_errors == 0
    finally:
        exporter.close()
        httpd.shutdown()
        httpd.server_close()


def test_otlp_exporter_never_blocks_or_dies_on_dead_collector():
    from gie_tpu.obs.otlp import OtlpSpanExporter

    # Nothing listens on this port: posts fail, exports drop, the sink
    # call stays instant.
    exporter = OtlpSpanExporter("http://127.0.0.1:1", timeout_s=0.2,
                                flush_interval_s=0.05, queue_max=4)
    try:
        t0 = time.monotonic()
        for i in range(32):  # overflow the bounded queue too
            exporter.export(_trace_dict())
        assert time.monotonic() - t0 < 0.5, "export blocked the caller"
        deadline = time.monotonic() + 3.0
        while time.monotonic() < deadline and exporter.post_errors == 0:
            time.sleep(0.05)
        assert exporter.post_errors > 0
        assert exporter.dropped > 0
        assert exporter.exported == 0
    finally:
        exporter.close()
    report = exporter.report()
    assert report["url"].endswith("/v1/traces")


def test_tracer_on_export_failures_never_fail_teardown():
    from gie_tpu.obs.trace import Tracer

    tracer = Tracer(1.0)

    def boom(trace):
        raise RuntimeError("sink bug")

    tracer.on_export = boom
    ctx = tracer.begin({})
    tracer.finish(ctx, "ok")  # must not raise
    assert tracer.exported_total == 1
