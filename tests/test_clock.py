"""Clock-seam unit tests (gie_tpu/runtime/clock.py; gie-twin,
docs/STORM.md "virtual clock").

The monotonic clock is a passthrough (pinned so the seam can never
drift from the stdlib semantics production runs on); the virtual clock
is a deterministic discrete-event core — time advances only when every
registered actor is parked, exactly one entry fires per advance, wakes
are serialized run-to-completion, and notifications never outrun the
advance rule."""

from __future__ import annotations

import threading
import time

import pytest

from gie_tpu.runtime.clock import MONOTONIC, MonotonicClock, VirtualClock


# --------------------------------------------------------------------------
# MonotonicClock: passthrough semantics
# --------------------------------------------------------------------------


def test_monotonic_clock_is_a_passthrough():
    clock = MonotonicClock()
    a = clock.now()
    assert abs(a - time.monotonic()) < 1.0
    ev = threading.Event()
    assert clock.wait_event(ev, 0.01) is False
    clock.set_event(ev)
    assert clock.wait_event(ev, 0.01) is True
    cond = threading.Condition()
    with cond:
        assert clock.wait(cond, 0.01) is False
    assert clock.actor_begin("x") is None  # registration is a no-op
    clock.actor_end(None)
    t = clock.actor_thread(lambda: None)
    t.start()
    t.join(1)
    assert not t.is_alive()


# --------------------------------------------------------------------------
# VirtualClock: the advance rule
# --------------------------------------------------------------------------


@pytest.fixture()
def vclock():
    clock = VirtualClock()
    yield clock
    clock.shutdown()


def test_virtual_sleep_advances_instantly_for_a_lone_actor(vclock):
    tok = vclock.actor_begin("solo")
    try:
        t0 = vclock.now()
        wall0 = time.monotonic()
        vclock.sleep(3600.0)  # an hour of virtual time...
        assert vclock.now() == pytest.approx(t0 + 3600.0)
        assert time.monotonic() - wall0 < 5.0  # ...in real milliseconds
    finally:
        vclock.actor_end(tok)


def test_virtual_time_waits_for_every_actor_to_park(vclock):
    """Two actors: the clock must not advance past the earlier deadline
    while the other actor is still active."""
    order: list = []

    def worker():
        vclock.sleep(10.0)
        order.append(("worker", vclock.now()))

    t = vclock.actor_thread(worker)
    tok = vclock.actor_begin("main")
    try:
        t.start()
        vclock.sleep(5.0)
        order.append(("main", vclock.now()))
        vclock.sleep(10.0)  # to 15.0: lets the worker's 10.0 fire first
    finally:
        vclock.actor_end(tok)
    t.join(5)
    assert order == [("main", 5.0), ("worker", 10.0)]
    assert vclock.now() == pytest.approx(15.0)


def test_virtual_same_deadline_fires_in_registration_order(vclock):
    hits: list = []

    def sleeper(name):
        vclock.sleep(1.0)
        hits.append(name)

    tok = vclock.actor_begin("main")
    threads = []
    try:
        for i in range(4):
            # Create-and-start per iteration: actor_thread registers at
            # CREATION (the clock must not advance past work the spawner
            # just scheduled), so pre-building the whole list would
            # count actors that never get to park.
            t = vclock.actor_thread(sleeper, args=(i,))
            threads.append(t)
            t.start()
            vclock.sleep(0.0)  # serialize: each sleeper parks in turn
        vclock.sleep(2.0)
    finally:
        vclock.actor_end(tok)
    for t in threads:
        t.join(5)
    assert hits == [0, 1, 2, 3]


def test_virtual_wait_event_times_out_and_wakes_on_set(vclock):
    ev = threading.Event()
    results: list = []

    def waiter():
        results.append(("timeout", vclock.wait_event(ev, 2.0),
                        vclock.now()))
        results.append(("set", vclock.wait_event(ev, 50.0), vclock.now()))

    t = vclock.actor_thread(waiter)
    tok = vclock.actor_begin("main")
    try:
        t.start()
        vclock.sleep(3.0)          # waiter's 2.0 timeout fires first
        vclock.set_event(ev)       # then the flag, long before 53.0
        vclock.sleep(0.1)
    finally:
        vclock.actor_end(tok)
    t.join(5)
    assert results[0] == ("timeout", False, 2.0)
    assert results[1][1] is True
    assert results[1][2] < 4.0  # woke on set_event, not the 50 s timeout


def test_virtual_condition_wait_notify_and_timeout(vclock):
    cond = threading.Condition()
    got: list = []

    def waiter():
        with cond:
            got.append(("first", vclock.wait(cond, 30.0), vclock.now()))
        with cond:
            got.append(("second", vclock.wait(cond, 1.5), vclock.now()))

    t = vclock.actor_thread(waiter)
    tok = vclock.actor_begin("main")
    try:
        t.start()
        vclock.sleep(1.0)
        with cond:
            vclock.notify_all(cond)  # wakes the first wait at t=1.0
        vclock.sleep(5.0)            # second wait times out at ~2.5
    finally:
        vclock.actor_end(tok)
    t.join(5)
    assert got[0] == ("first", True, 1.0)
    assert got[1][0] == "second" and got[1][1] is False
    assert got[1][2] == pytest.approx(2.5)


def test_virtual_ephemeral_unregistered_thread_can_park(vclock):
    """A thread that never registered (warmup helpers, teardown) may
    still sleep: it is counted as an actor only for the park."""
    wall0 = time.monotonic()
    vclock.sleep(100.0)
    assert vclock.now() == pytest.approx(100.0)
    assert time.monotonic() - wall0 < 5.0


def test_virtual_serialized_wakes_run_to_completion(vclock):
    """Entries readied at the same instant fire one at a time, and a
    woken actor runs to its NEXT PARK before any other entry fires —
    the serialization the storm's decision determinism is built on.
    Each waiter's wake/work records must therefore be adjacent: another
    actor's wake interleaving between them would mean two woken actors
    ran concurrently."""
    events: list = []
    ev = threading.Event()

    def waiter(i):
        vclock.wait_event(ev, 60.0)
        events.append(("wake", i))
        events.append(("work", i))  # no park between: one atomic run

    tok = vclock.actor_begin("main")
    threads = []
    try:
        for i in range(6):
            t = vclock.actor_thread(waiter, args=(i,))
            threads.append(t)
            t.start()
            vclock.sleep(0.0)
        vclock.set_event(ev)  # readies all six at the current instant
        vclock.sleep(1.0)
    finally:
        vclock.actor_end(tok)
    for t in threads:
        t.join(5)
    assert len(events) == 12
    pairs = [events[j:j + 2] for j in range(0, 12, 2)]
    for wake, work in pairs:
        assert wake[0] == "wake" and work[0] == "work"
        assert wake[1] == work[1], (
            f"interleaved wakes: {events} — woken actors must run to "
            "completion one at a time")
    # Readied-at-the-same-instant entries fire in registration order.
    assert [w[1] for w, _ in pairs] == [0, 1, 2, 3, 4, 5]


def test_default_monotonic_singleton_is_not_virtual():
    assert MONOTONIC.is_virtual is False
    assert VirtualClock.is_virtual is True
