"""C++ exposition parser (native/promparse.cc) parity vs the Python path.

Same policy as the chunker (tests/test_native.py): the library is built on
demand by conftest; when present, the native fast path must be
bit-identical to the pure-Python parser on every mapped-server format and
the exposition format's edge cases (escaped label values, +Inf,
timestamps, freshest-LoRA-series rule, value-label info gauges).
"""

import pytest

from gie_tpu.metricsio import native
from gie_tpu.metricsio.mappings import BY_NAME, VLLM
from gie_tpu.metricsio.scrape import parse_scrape
from gie_tpu.utils.lora import LoraRegistry

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native/libgiepromparse.so not built"
)


def both(text, mapping=VLLM):
    py = parse_scrape(text, mapping, LoraRegistry(), use_native=False)
    nat = parse_scrape(text, mapping, LoraRegistry(), use_native=True)
    assert py == nat, f"\npython: {py}\nnative: {nat}"
    return nat


def test_basic_gauges_and_comments():
    out, _, _ = both(
        "# HELP vllm:num_requests_waiting x\n"
        "# TYPE vllm:num_requests_waiting gauge\n"
        "vllm:num_requests_waiting 7\n"
        "vllm:num_requests_running 3 1700000000000\n"
        "vllm:kv_cache_usage_perc 0.42\n"
        "unrelated_metric{a=\"b\"} 9\n"
    )
    assert out and len(out) >= 3


def test_value_label_info_gauge():
    out, _, _ = both(
        'vllm:cache_config_info{block_size="16",num_gpu_blocks="2048"} 1\n'
        "vllm:num_requests_waiting 0\n"
        "vllm:num_requests_running 0\n"
        "vllm:kv_cache_usage_perc 0\n"
    )
    from gie_tpu.sched.constants import Metric

    assert out[Metric.BLOCK_SIZE] == 16.0
    assert out[Metric.NUM_BLOCKS] == 2048.0


def test_escaped_label_values_and_label_order():
    both(
        'vllm:num_requests_waiting{engine="a\\"b\\\\c",zone="x"} 5\n'
        "vllm:num_requests_running 1\n"
        "vllm:kv_cache_usage_perc 0.5\n"
    )


def test_inf_values():
    both(
        "vllm:num_requests_waiting +Inf\n"
        "vllm:num_requests_running -Inf\n"
        "vllm:kv_cache_usage_perc 0.1\n"
    )


def test_lora_freshest_series_wins():
    text = (
        "vllm:num_requests_waiting 1\n"
        "vllm:num_requests_running 1\n"
        "vllm:kv_cache_usage_perc 0.2\n"
        'vllm:lora_requests_info{max_lora="4",running_lora_adapters='
        '"old-a,old-b",waiting_lora_adapters=""} 100\n'
        'vllm:lora_requests_info{max_lora="4",running_lora_adapters='
        '"new-a",waiting_lora_adapters="new-w"} 200\n'
    )
    reg_py, reg_nat = LoraRegistry(), LoraRegistry()
    py = parse_scrape(text, VLLM, reg_py, use_native=False)
    nat = parse_scrape(text, VLLM, reg_nat, use_native=True)
    assert py == nat
    # The fresher (ts=200) series won: one active, one waiting.
    assert len(nat[1]) == 1 and len(nat[2]) == 1


def test_lora_underscore_spelling():
    both(
        "vllm:num_requests_waiting 1\n"
        "vllm:num_requests_running 1\n"
        "vllm:kv_cache_usage_perc 0.2\n"
        'vllm_lora_requests_info{max_lora="2",running_lora_adapters="a",'
        'waiting_lora_adapters=""} 5\n'
    )


def test_absent_metrics_identical():
    both("totally_unrelated 1\n")


def test_every_mapped_server_format():
    for name, mapping in BY_NAME.items():
        text = (
            f"{mapping.queued.name}"
            + (
                "{"
                + ",".join(
                    f'{k}="{v}"' for k, v in mapping.queued.labels.items()
                )
                + "}"
                if mapping.queued.labels
                else ""
            )
            + " 4\n"
            f"{mapping.running.name} 2\n"
            f"{mapping.kv_util.name} 0.3\n"
        )
        both(text, mapping)


def test_stub_fleet_parity_under_load():
    from gie_tpu.simulator.vllm_stub import StubConfig, VLLMStub

    stub = VLLMStub(StubConfig(max_lora=4), name="p")
    for i in range(30):
        stub.submit(b"y" * 1500, decode_tokens=20, lora=f"ad-{i % 5}")
    stub.step(0.05)
    both(stub.metrics_text())


def test_lora_freshest_across_both_spellings():
    """A fresher '_'-spelled series must beat a staler ':' series in BOTH
    paths (the native scanner collects both spellings in one pass)."""
    text = (
        "vllm:num_requests_waiting 1\n"
        "vllm:num_requests_running 1\n"
        "vllm:kv_cache_usage_perc 0.2\n"
        'vllm:lora_requests_info{max_lora="4",running_lora_adapters='
        '"stale",waiting_lora_adapters=""} 100\n'
        'vllm_lora_requests_info{max_lora="4",running_lora_adapters='
        '"fresh",waiting_lora_adapters=""} 200\n'
    )
    reg_py, reg_nat = LoraRegistry(), LoraRegistry()
    py = parse_scrape(text, VLLM, reg_py, use_native=False)
    nat = parse_scrape(text, VLLM, reg_nat, use_native=True)
    assert py == nat
    assert nat[1] == [reg_nat.id_for("fresh")]


def test_malformed_value_label_rejected_by_both():
    """stod prefix-parsing must not diverge from Python float(): a
    non-numeric value label is dropped by both paths."""
    out, _, _ = both(
        'vllm:cache_config_info{block_size="16 tokens",num_gpu_blocks='
        '"0x800"} 1\n'
        "vllm:num_requests_waiting 2\n"
        "vllm:num_requests_running 0\n"
        "vllm:kv_cache_usage_perc 0\n"
    )
    from gie_tpu.sched.constants import Metric

    assert Metric.BLOCK_SIZE not in out
    assert Metric.NUM_BLOCKS not in out


def test_bytes_input_parity():
    text = (
        "vllm:num_requests_waiting 5\n"
        "vllm:num_requests_running 2\n"
        "vllm:kv_cache_usage_perc 0.7\n"
    )
    s = parse_scrape(text, VLLM, LoraRegistry(), use_native=True)
    b = parse_scrape(text.encode(), VLLM, LoraRegistry(), use_native=True)
    p = parse_scrape(text.encode(), VLLM, LoraRegistry(), use_native=False)
    assert s == b == p
