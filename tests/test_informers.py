"""Informer/lister cached-client layer (SURVEY C3, reference client-go/)."""

from gie_tpu.api import types as api
from gie_tpu.api.informers import SharedInformerFactory
from gie_tpu.controller.cluster import FakeCluster
from gie_tpu.datastore.objects import Pod


def make_pool(name="pool"):
    return api.InferencePool(
        metadata=api.ObjectMeta(name=name),
        spec=api.InferencePoolSpec(
            selector=api.LabelSelector(matchLabels={"app": "m"}),
            targetPorts=[api.Port(8000)],
            endpointPickerRef=api.EndpointPickerRef(
                name="epp", port=api.Port(9002)),
        ),
    )


def setup():
    cluster = FakeCluster()
    cluster.apply_pool(make_pool())
    cluster.apply_pod(Pod(name="p0", labels={"app": "m"}, ip="10.0.0.1"))
    factory = SharedInformerFactory(cluster, "default",
                                    pool_names=["pool"])
    return cluster, factory


def test_cache_sync_and_listers():
    cluster, factory = setup()
    assert not factory.wait_for_cache_sync()
    factory.start()
    assert factory.wait_for_cache_sync()
    pods = factory.pods().lister()
    pools = factory.pools().lister()
    assert [p.name for p in pods.list("default")] == ["p0"]
    assert pools.get("default", "pool").metadata.name == "pool"
    # Listers read the CACHE: a direct cluster write without an event is
    # invisible until its watch event lands (cached-read semantics).
    assert pods.get("default", "p0").ip == "10.0.0.1"


def test_watch_events_update_cache_and_fire_handlers():
    cluster, factory = setup()
    events = []
    factory.pods().add_event_handler(
        lambda t, key, obj: events.append((t, key[1])))
    factory.start()
    assert ("ADDED", "p0") in events

    cluster.apply_pod(Pod(name="p1", labels={"app": "m"}, ip="10.0.0.2"))
    assert ("ADDED", "p1") in events
    assert factory.pods().lister().get("default", "p1").ip == "10.0.0.2"

    cluster.apply_pod(Pod(name="p1", labels={"app": "m"}, ip="10.0.0.9"))
    assert ("MODIFIED", "p1") in events
    assert factory.pods().lister().get("default", "p1").ip == "10.0.0.9"

    cluster.delete_pod("default", "p1")
    assert ("DELETED", "p1") in events
    assert factory.pods().lister().get("default", "p1") is None


def test_pool_informer_follows_events():
    cluster, factory = setup()
    factory.start()
    pool = make_pool()
    pool.metadata.labels["tier"] = "gold"
    cluster.apply_pool(pool)
    assert factory.pools().lister().get(
        "default", "pool").metadata.labels["tier"] == "gold"
    cluster.delete_pool("default", "pool")
    assert factory.pools().lister().get("default", "pool") is None
    assert factory.pools().lister().list() == []


def test_late_handler_gets_replay():
    """client-go semantics: a handler added after sync receives synthetic
    ADDED events for the existing cache contents."""
    cluster, factory = setup()
    factory.start()
    seen = []
    factory.pods().add_event_handler(
        lambda t, key, obj: seen.append((t, key[1])))
    assert seen == [("ADDED", "p0")]


def test_start_skips_keys_cached_by_racing_events():
    """An event landing between subscribe() and start() must not produce a
    duplicate ADDED or regress the cache to the stale list snapshot."""
    cluster, factory = setup()
    events = []
    factory.pods().add_event_handler(
        lambda t, key, obj: events.append((t, key[1])))
    # Simulate the race: the watch delivers a MODIFIED pod before start().
    cluster.subscribe(factory.pods().on_event)
    cluster.apply_pod(Pod(name="p0", labels={"app": "m"}, ip="10.0.0.77"))
    factory.pods().start()
    assert events.count(("ADDED", "p0")) == 1
    # Cache kept the fresher watch object, not the list snapshot.
    assert factory.pods().lister().get("default", "p0").ip == "10.0.0.77"


def test_namespace_scoping():
    """Events outside the factory's namespace are dropped (cache scoped to
    the pool namespace, reference controller_manager.go:45-68)."""
    cluster, factory = setup()
    factory.start()
    cluster.apply_pod(Pod(name="alien", namespace="other",
                          labels={"app": "m"}, ip="10.0.9.9"))
    assert factory.pods().lister().get("other", "alien") is None
    assert all(p.namespace == "default"
               for p in factory.pods().lister().list())
