"""Fault-point coverage meta-test (ISSUE 7, docs/RESILIENCE.md).

Every fault point registered in ``gie_tpu.resilience.faults.CATALOG``
must be exercised by at least one test — a new injection site cannot
land untested. "Exercised" means some test module other than this one
names the point in a string literal (the injector refuses unknown
names, so a literal in a test is a live FaultRule/spec reference, not
prose). The reverse direction holds too: a point named by tests but
missing from the catalog is a stale reference the injector would
reject at runtime.

Also pins the weave itself: every catalog point must appear in
gie_tpu/ source (a catalog entry with no woven call site is dead
configuration).
"""

from __future__ import annotations

import ast
import os

from gie_tpu.resilience.faults import CATALOG

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
PKG = os.path.join(REPO, "gie_tpu")
SELF = os.path.basename(__file__)


def _string_literals(path: str) -> set:
    """All string constants in a python file (AST-level, so comments and
    docstring prose don't count as coverage... they do, actually — a
    docstring IS a Constant node. Filter those out by keeping only
    strings that exactly equal a catalog point, which prose sentences
    never do)."""
    with open(path, "r", encoding="utf-8") as fh:
        tree = ast.parse(fh.read())
    return {
        node.value
        for node in ast.walk(tree)
        if isinstance(node, ast.Constant) and isinstance(node.value, str)
    }


def _exact_point_literals(root: str, skip: set) -> dict:
    """point -> sorted files naming it as an exact string literal."""
    hits: dict = {p: [] for p in CATALOG}
    for dirpath, _dirs, files in os.walk(root):
        for fn in sorted(files):
            if not fn.endswith(".py") or fn in skip:
                continue
            path = os.path.join(dirpath, fn)
            lits = _string_literals(path)
            for point in CATALOG:
                if point in lits:
                    hits[point].append(os.path.relpath(path, REPO))
    return hits


def test_every_fault_point_is_exercised_by_a_test():
    hits = _exact_point_literals(HERE, skip={SELF})
    uncovered = sorted(p for p, files in hits.items() if not files)
    assert not uncovered, (
        f"fault points registered in CATALOG but exercised by no test: "
        f"{uncovered} — every injection site needs at least one test "
        f"driving a FaultRule through it (tests/test_resilience.py and "
        f"tests/test_chaos.py hold the existing ones)")


def test_every_fault_point_is_woven_into_source():
    hits = _exact_point_literals(
        PKG, skip={"faults.py"})  # the registry itself doesn't count
    unwoven = sorted(p for p, files in hits.items() if not files)
    assert not unwoven, (
        f"fault points registered in CATALOG but woven into no gie_tpu/ "
        f"call site: {unwoven} — delete the catalog entry or add the "
        f"faults.check()/fire() weave")


def test_no_stale_point_names_in_tests():
    """Any 'x.y'-shaped literal passed to FaultRule dicts/specs in tests
    must be a registered point. Heuristic: exact literals that LOOK like
    fault points (lowercase dotted pairs over the catalog's vocabulary
    of subsystem prefixes) but aren't registered."""
    prefixes = {p.split(".")[0] for p in CATALOG}
    stale = set()
    for dirpath, _dirs, files in os.walk(HERE):
        for fn in sorted(files):
            if not fn.endswith(".py") or fn == SELF:
                continue
            for lit in _string_literals(os.path.join(dirpath, fn)):
                parts = lit.split(".")
                if (len(parts) == 2 and parts[0] in prefixes
                        and parts[1].isidentifier()
                        and lit not in CATALOG
                        and not lit.endswith((".py", ".md"))):
                    stale.add(lit)
    # Known non-point dotted literals living in test files (module
    # attributes etc.) are excluded by the isidentifier/prefix filter;
    # anything left is a typo'd fault point waiting to silently no-op.
    assert not stale, f"dotted literals that look like fault points: {stale}"


def test_federation_points_woven_into_the_exchange():
    """ISSUE 12: the three peer.* points must be woven into the
    federation exchange specifically (the generic weave test above only
    proves SOME gie_tpu file names them)."""
    path = os.path.join(PKG, "federation", "exchange.py")
    lits = _string_literals(path)
    for point in ("peer.poll", "peer.publish", "peer.partition"):
        assert point in lits, f"{point} not woven in federation/exchange.py"
