"""gie-learn: offline-trained multiplicative policies (docs/LEARNED.md).

Pins the PR 17 contracts end to end: byte-deterministic training,
fingerprint-keyed split hygiene, the learned scorer's mesh-parity and
numpy-reference bounds, artifact versioning/integrity, the twin judge's
verdict (including the committed promotion artifact), and the obs-side
feeds (dump rotation, the harvest CLI, the policy zpage/metrics stamp).
"""

import dataclasses
import functools
import json
import os
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gie_tpu.learn import artifact as artifact_mod
from gie_tpu.learn import dataset as dataset_mod
from gie_tpu.learn import judge as judge_mod
from gie_tpu.learn import policy as policy_mod
from gie_tpu.learn import train as train_mod

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURE_DUMP = os.path.join(
    REPO, "tests", "fixtures", "learn", "storm-fixture-flightrec.json")
COMMITTED_ARTIFACT = os.path.join(
    REPO, "config", "policy", "storm-lora-v1.json")
COMMITTED_JUDGMENT = os.path.join(REPO, "LEARNJUDGE_r01.json")


# ---------------------------------------------------------------- helpers

def _record(queue, kv, load, latency_ms, *, outcome="2xx", seq=0, **over):
    """One v1 decision record with a closed serve outcome."""
    rec = {
        "v": 1, "seq": seq, "ts": 1000.0 + seq,
        "chosen": "10.0.0.1", "served": "10.0.0.1",
        "outcome": outcome, "serve_latency_ms": latency_ms,
        "fallback_rank": 0,
        "scorers": {"queue": queue, "kv_cache": kv, "assumed_load": load},
    }
    rec.update(over)
    return rec


def _synthetic_dumps(n_groups=3, rows=40, seed=0):
    """Dumps whose latency is EXACTLY the multiplicative model: latency
    falls as the normalized columns rise, so the ridge must recover
    positive exponents on queue/kv_cache."""
    rng = np.random.default_rng(seed)
    dumps = []
    for g in range(n_groups):
        records = []
        for i in range(rows):
            q = float(rng.uniform(0.05, 1.0))
            kv = float(rng.uniform(0.05, 1.0))
            load = float(rng.uniform(0.2, 1.0))
            latency = 80.0 * q ** -1.5 * kv ** -0.8
            records.append(_record(q, kv, load, round(latency, 1),
                                   seq=i))
        dumps.append((f"fp-{seed}-{g:02d}", records))
    return dumps


# ------------------------------------------------------ dataset + splits

def test_build_dataset_counts_every_skip_reason():
    """Satellite 3 pin: records a serve outcome never closed, 5xx, and
    resets are SKIPPED WITH A COUNTED REASON — never a KeyError, and
    never a regression target (a fast local-reply 503 would otherwise
    teach the policy that the sick endpoint is the fastest one)."""
    records = [
        _record(0.5, 0.5, 0.5, 12.0, seq=0),                  # trains
        _record(0.5, 0.5, 0.5, 3.0, outcome="5xx", seq=1),
        _record(0.5, 0.5, 0.5, 9.0, outcome="reset", seq=2),
        _record(0.5, 0.5, 0.5, 9.0, outcome="closed", seq=3),
        _record(0.5, 0.5, 0.5, None, outcome="shed", seq=4),
        _record(0.5, 0.5, 0.5, None, outcome="unavailable", seq=5),
        _record(0.5, 0.5, 0.5, None, outcome="picked", seq=6),
        _record(0.5, 0.5, 0.5, 8.0, outcome="weird", seq=7),
        _record(0.5, 0.5, 0.5, 8.0, served="", seq=8),
        _record(0.5, 0.5, 0.5, 8.0, served="10.0.0.9", seq=9),
        _record(0.5, 0.5, 0.5, -1.0, seq=10),
        _record(0.5, 0.5, 0.5, 8.0, scorers=None, seq=11),
        "junk",
    ]
    ds = dataset_mod.build_dataset([("fp", records)])
    assert len(ds) == 1
    assert ds.skipped == {
        "error_5xx": 1, "reset": 1, "closed": 1, "shed": 1,
        "unavailable": 1, "unresolved": 1, "outcome_weird": 1,
        "missing_served": 1, "failover": 1, "missing_latency": 1,
        "missing_scorers": 1, "junk_entry": 1,
    }


def test_build_dataset_defaults_missing_column_to_neutral():
    rec = _record(0.5, 0.5, 0.5, 10.0)
    del rec["scorers"]["assumed_load"]
    ds = dataset_mod.build_dataset([("fp", [rec])])
    assert len(ds) == 1
    # 1.0 is the multiplicative neutral (col**w == 1) and the default is
    # counted, never silent.
    assert float(ds.features[0, 2]) == 1.0
    assert ds.skipped == {"defaulted_assumed_load": 1}


def test_load_records_tolerates_outcomeless_records():
    """The satellite-3 bugfix at the loader layer: a record the serve
    path never closed (no ``served``, no latency) loads fine — skipping
    it is the dataset builder's counted job, not a loader crash."""
    half_open = {"v": 1, "seq": 0, "chosen": "10.0.0.1",
                 "outcome": "picked"}
    stats = {}
    out = dataset_mod.load_records(
        json.dumps([half_open, {"seq": 1}, 42]), stats=stats)
    assert [r["seq"] for r in out] == [0, 1]
    assert out[1]["v"] == 0  # pre-version record stamped, kept
    assert stats == {"junk_entry": 1, "unversioned": 1}


def test_split_by_fingerprint_never_leaks_groups():
    ds = dataset_mod.build_dataset(_synthetic_dumps(n_groups=8, rows=5))
    train_rows, eval_rows = dataset_mod.split_by_fingerprint(
        ds, eval_fraction=0.25, seed=3)
    assert train_rows.size + eval_rows.size == len(ds)
    train_fps = {ds.fingerprints[g] for g in ds.group[train_rows]}
    eval_fps = {ds.fingerprints[g] for g in ds.group[eval_rows]}
    # The leakage guard: a schedule fingerprint lives on ONE side only.
    assert not (train_fps & eval_fps)
    assert eval_fps  # forced non-empty with >1 group


def test_split_forces_one_eval_group_and_zero_fraction_is_empty():
    ds = dataset_mod.build_dataset(_synthetic_dumps(n_groups=2, rows=3))
    # A fraction small enough that no hash point lands under it still
    # yields one whole eval group (never silently train-on-everything).
    _, eval_rows = dataset_mod.split_by_fingerprint(
        ds, eval_fraction=1e-12, seed=0)
    assert eval_rows.size > 0
    _, eval_rows = dataset_mod.split_by_fingerprint(
        ds, eval_fraction=0.0, seed=0)
    assert eval_rows.size == 0
    with pytest.raises(ValueError, match="eval_fraction"):
        dataset_mod.split_by_fingerprint(ds, eval_fraction=1.0)


def test_content_fingerprint_is_stable_and_content_keyed():
    a = _synthetic_dumps(1, rows=4)[0][1]
    assert (dataset_mod.content_fingerprint(a)
            == dataset_mod.content_fingerprint([dict(r) for r in a]))
    b = [dict(r) for r in a]
    b[0]["serve_latency_ms"] = 999.0
    assert (dataset_mod.content_fingerprint(a)
            != dataset_mod.content_fingerprint(b))


# ------------------------------------------------------------- training

def test_train_is_byte_deterministic():
    """The determinism contract: same dumps + seed => byte-identical
    artifact text (checksum and all)."""
    fp, records = dataset_mod.load_dump(FIXTURE_DUMP)
    dumps = [(fp, records)]
    a = artifact_mod.dumps_artifact(
        train_mod.train(dumps, seed=7, eval_fraction=0.0, l2=1.0))
    b = artifact_mod.dumps_artifact(
        train_mod.train(dumps, seed=7, eval_fraction=0.0, l2=1.0))
    assert a == b
    c = artifact_mod.dumps_artifact(
        train_mod.train(dumps, seed=8, eval_fraction=0.0, l2=1.0))
    assert a != c


def test_train_recovers_positive_exponents_and_projects_negatives():
    art = train_mod.train(_synthetic_dumps(), seed=0, eval_fraction=0.25)
    w = artifact_mod.artifact_weight_values(art)
    # The synthetic latency is literally 80 * q^-1.5 * kv^-0.8: the ridge
    # must find queue and kv_cache, and the uninformative column (load
    # never enters the latency) stays at the non-negative floor.
    assert float(w["queue"]) > 0.5
    assert float(w["kv_cache"]) > 0.3
    assert float(w["assumed_load"]) >= 0.0
    assert art["provenance"]["n_eval"] > 0
    assert art["provenance"]["eval_fingerprints"]  # whole groups held out
    assert art["provenance"]["trained_at"] > 0  # from the data, not wall


def test_train_refuses_empty_corpus():
    with pytest.raises(ValueError, match="no trainable rows"):
        train_mod.train(
            [("fp", [_record(0.5, 0.5, 0.5, 5.0, outcome="5xx")])])


# ------------------------------------------------------- policy numerics

def _ulp_diff(a: np.ndarray, b: np.ndarray) -> int:
    """Max ULP distance between two strictly-positive float32 arrays
    (positive IEEE-754 floats are monotone as int32 bit patterns)."""
    ia = np.asarray(a, np.float32).view(np.int32).astype(np.int64)
    ib = np.asarray(b, np.float32).view(np.int32).astype(np.int64)
    return int(np.abs(ia - ib).max())


def test_multiplicative_total_matches_numpy_reference_within_ulps():
    """Eager-vs-jit bitwise equality is NOT a property of any fused
    float formula (XLA folds exp(a)*exp(b) and contracts FMAs), so the
    algebra is pinned against the plain-numpy reference with a measured
    ULP bound instead; the bitwise claims live in the mesh-parity tests
    below where they are real (same formula, jit vs jit)."""
    rng = np.random.default_rng(11)
    stacked = rng.uniform(0.0, 1.0, (3, 16, 32)).astype(np.float32)
    wvec = np.asarray([0.24, 3.07, 1.5], np.float32)
    got = np.asarray(jax.jit(policy_mod.multiplicative_total)(
        jnp.asarray(stacked), jnp.asarray(wvec)))
    ref = policy_mod.multiplicative_total_reference(stacked, wvec)
    assert got.shape == ref.shape and (got > 0).all() and (ref > 0).all()
    assert _ulp_diff(got, ref) <= 128


def test_multiplicative_total_zero_column_hits_eps_floor_not_inf():
    stacked = jnp.zeros((2, 1, 3), jnp.float32)
    wvec = jnp.asarray([1.0, 2.0], jnp.float32)
    total = np.asarray(policy_mod.multiplicative_total(stacked, wvec))
    assert np.isfinite(total).all() and (total > 0).all()


def test_float32_hex_is_a_bit_roundtrip():
    for v in (0.0, 1.0, 3.0714285373687744, np.float32(1e-6),
              0.1, 2.0 ** -126):
        hexed = policy_mod.float32_hex(v)
        back = policy_mod.float32_from_hex(hexed)
        assert np.float32(v).tobytes() == np.float32(back).tobytes()
    with pytest.raises(ValueError, match="8 hex chars"):
        policy_mod.float32_from_hex("abcd")


def test_weights_from_mapping_rejects_unknowns_and_zeros_missing():
    w = policy_mod.weights_from_mapping({"queue": 2.0, "kv_cache": 1.0})
    assert float(w.queue) == 2.0 and float(w.session) == 0.0
    with pytest.raises(ValueError, match="unknown scorer columns"):
        policy_mod.weights_from_mapping({"vibes": 1.0})


# --------------------------------------------------- mesh parity (PR 15)

def _loaded_pool(m_valid, m_slots, seed):
    from gie_tpu.utils.testing import make_endpoints

    rng = np.random.default_rng(seed)
    return make_endpoints(
        m_valid,
        queue=rng.integers(40, 120, m_valid).tolist(),
        kv=rng.uniform(0.1, 0.9, m_valid).tolist(),
        m_slots=m_slots,
    )


@pytest.mark.parametrize("n_mesh", [1, 2, 4, 8])
@pytest.mark.parametrize("picker", ["sinkhorn", "topk"])
def test_learned_scorer_mesh_parity(n_mesh, picker):
    """The PR 15 bitwise rule extended to the learned scorer: the
    mesh-sharded jitted cycle must match the single-device jitted cycle
    BIT FOR BIT at every mesh size — the log-space einsum splits N/M
    exactly like the blend's, never the column axis."""
    from gie_tpu.parallel.mesh import make_mesh, sharded_cycle
    from gie_tpu.sched.profile import ProfileConfig, scheduling_cycle
    from gie_tpu.sched.types import SchedState
    from gie_tpu.utils.testing import make_requests

    assert len(jax.devices()) >= 8
    cfg = ProfileConfig(picker=picker, scorer="learned")
    weights = policy_mod.weights_from_mapping(
        {"queue": 0.2391, "kv_cache": 3.0714, "assumed_load": 0.0})
    eps = _loaded_pool(37, 64, seed=21)
    state = SchedState.init(m=64)
    single = jax.jit(
        functools.partial(scheduling_cycle, cfg=cfg, predictor_fn=None))
    sharded = sharded_cycle(make_mesh(n_mesh), cfg, None)
    for wave in range(2):
        prompts = [b"LRN %d " % (i % 4) * 30 + b"w%d q%d" % (wave, i)
                   for i in range(64)]
        reqs = make_requests(64, prompts=prompts, m_slots=64)
        key = jax.random.PRNGKey(300 + wave)
        r1, s1 = single(state, reqs, eps, weights, key, None)
        r2, s2 = sharded(state, reqs, eps, weights, key, None)
        np.testing.assert_array_equal(
            np.asarray(r1.indices), np.asarray(r2.indices))
        np.testing.assert_array_equal(
            np.asarray(r1.status), np.asarray(r2.status))
        np.testing.assert_array_equal(
            np.asarray(s1.ot_v), np.asarray(s2.ot_v))
        state = s1
    assert (np.asarray(r1.indices[:, 0]) >= 0).any()  # non-vacuous


def test_profile_config_scorer_guards():
    from gie_tpu.sched.profile import ProfileConfig

    with pytest.raises(ValueError, match="blend.*learned|learned.*blend"):
        ProfileConfig(scorer="sum")
    with pytest.raises(ValueError, match="use_pallas_topk"):
        ProfileConfig(scorer="learned", use_pallas_topk=True)
    with pytest.raises(ValueError, match="pd_disaggregation"):
        ProfileConfig(scorer="learned", pd_disaggregation=True)


def test_feature_schema_tracks_profile_columns():
    from gie_tpu.sched.profile import ProfileConfig, feature_schema

    base = feature_schema(ProfileConfig(
        enable_prefix=False, enable_session=False, enable_lora=False))
    assert base == ("queue", "kv_cache", "assumed_load")
    full = feature_schema(ProfileConfig(), has_predictor=True)
    assert set(dataset_mod.DEFAULT_FEATURES) < set(full)
    assert "latency" in full


# ------------------------------------------------------------- artifacts

def _valid_artifact():
    return artifact_mod.build_artifact(
        {"queue": 0.25, "kv_cache": 3.0, "assumed_load": 0.0},
        ("queue", "kv_cache", "assumed_load"),
        {"seed": 0, "trained_at": 1234.5})


def test_artifact_roundtrip_and_checksum_tamper():
    art = _valid_artifact()
    assert artifact_mod.loads_artifact(
        artifact_mod.dumps_artifact(art)) == art
    tampered = json.loads(artifact_mod.dumps_artifact(art))
    tampered["weights"]["queue"]["hex"] = policy_mod.float32_hex(9.0)
    tampered["weights"]["queue"]["value"] = 9.0
    with pytest.raises(ValueError, match="checksum mismatch"):
        artifact_mod.loads_artifact(json.dumps(tampered))


def test_artifact_rejects_newer_major_tolerates_additive_fields():
    art = json.loads(artifact_mod.dumps_artifact(_valid_artifact()))
    newer = dict(art, schema="gie-learn-policy/2")
    newer["checksum"] = artifact_mod.compute_checksum(newer)
    with pytest.raises(ValueError, match="newer than this build"):
        artifact_mod.validate_artifact(newer)
    # Additive unknown fields are forward-compatible by contract.
    grown = dict(art, optimizer_state={"future": True})
    grown["checksum"] = artifact_mod.compute_checksum(grown)
    artifact_mod.validate_artifact(grown)


def test_artifact_rejects_half_edited_weight():
    art = json.loads(artifact_mod.dumps_artifact(_valid_artifact()))
    art["weights"]["queue"]["value"] = 7.0  # hex left untouched
    art["checksum"] = artifact_mod.compute_checksum(art)
    with pytest.raises(ValueError, match="disagrees with its hex bits"):
        artifact_mod.validate_artifact(art)


def test_validate_feature_schema_subset_rule():
    art = _valid_artifact()
    artifact_mod.validate_feature_schema(
        art, ("queue", "kv_cache", "assumed_load", "prefix"))
    with pytest.raises(ValueError, match="blinded policy"):
        artifact_mod.validate_feature_schema(art, ("queue", "kv_cache"))


def test_committed_policy_artifact_is_valid_and_promoted():
    """The PR's acceptance artifact: the checked-in trained policy must
    validate (checksum intact) and carry a PROMOTE judgment covering
    BOTH a seeded storm and a replayed trace at matching schedule
    fingerprints."""
    art = artifact_mod.load_artifact(COMMITTED_ARTIFACT)
    judgment = art["judgment"]
    judge_mod.validate(judgment)
    assert judgment["promote"] is True
    kinds = {row["kind"] for row in judgment["scenarios"]}
    assert {"storm", "trace_replay"} <= kinds
    for row in judgment["scenarios"]:
        assert row["passed"] and all(row["gates"].values())
        assert (row["heuristic"]["schedule_fingerprint"]
                == row["learned"]["schedule_fingerprint"])
    with open(COMMITTED_JUDGMENT) as fh:
        standalone = json.load(fh)
    judge_mod.validate(standalone)
    assert standalone["promote"] is True
    # The standalone judgment and the one attached to the artifact are
    # the same verdict about the same weight bits.
    assert standalone["policy_checksum"] == judgment["policy_checksum"]
    assert standalone["policy_weights"] == judgment["policy_weights"]


# ----------------------------------------------------------------- judge

def test_judge_gate_semantics():
    heur = {"goodput_tokens_per_s": 100.0, "slo_attainment": 0.9,
            "ttft_p99_s": 1.0}
    better = {"goodput_tokens_per_s": 101.0, "slo_attainment": 0.91,
              "ttft_p99_s": 1.05}
    gates = judge_mod._gate(heur, better, p99_tolerance=1.10)
    assert all(gates.values())
    worse = dict(better, ttft_p99_s=1.2)
    assert not judge_mod._gate(heur, worse, 1.10)["p99"]
    # No completions on either side is a tie, not a crash.
    none_vs_none = judge_mod._gate(
        dict(heur, ttft_p99_s=None), dict(better, ttft_p99_s=None), 1.1)
    assert none_vs_none["p99"]


def test_judge_validate_rejects_mismatched_fingerprints():
    with open(COMMITTED_JUDGMENT) as fh:
        judgment = json.load(fh)
    judgment["scenarios"][0]["heuristic"]["schedule_fingerprint"] = "x"
    with pytest.raises(ValueError, match="different schedules"):
        judge_mod.validate(judgment)


def test_judge_requires_some_scenario():
    with pytest.raises(ValueError, match="at least one"):
        judge_mod.judge(_valid_artifact())


def test_judge_promotes_learned_over_misweighted_heuristic(monkeypatch):
    """Satellite 4's synthetic-dump verdict: train a tiny policy from a
    synthetic corpus, mis-weight the incumbent heuristic (negative
    queue weight — it PREFERS full queues), and the twin judge must
    return PROMOTE with matching schedule fingerprints on both cards.

    The mis-tuned profile also swaps sinkhorn for topk and drops the
    saturation filter ON BOTH SIDES (the judge hands the same profile to
    both cards) — those guardrails exist precisely to mask a bad scorer,
    and with them on, shed dynamics dominate the verdict instead of the
    scorer under test."""
    from gie_tpu.resilience import scenarios as scenarios_mod
    from gie_tpu.sched import config as config_mod
    from gie_tpu.sched.types import Weights

    art = train_mod.train(_synthetic_dumps(), seed=0, eval_fraction=0.25)

    real_tuned = config_mod.tuned_profile

    def mis_tuned():
        prof, _ = real_tuned()
        prof = dataclasses.replace(
            prof, picker="topk", enable_saturation=False)
        return prof, Weights(
            queue=jnp.float32(-3.0), kv_cache=jnp.float32(-1.0),
            prefix=jnp.float32(0.0), lora=jnp.float32(0.0),
            assumed_load=jnp.float32(0.0), latency=jnp.float32(0.0),
            session=jnp.float32(0.0))

    monkeypatch.setattr(config_mod, "tuned_profile", mis_tuned)
    scn = scenarios_mod.Scenario(
        name="learn-judge-unit", description="mis-weighted incumbent",
        seed=7, rules={}, drive={"storm": {
            "base_qps": 24.0, "duration_s": 4.0, "ttft_slo_s": 1.5,
            "queue_limit": 3.0, "max_concurrency": 96,
            "traffic": {"n_sessions": 12, "decode_tokens_mean": 16.0,
                        "sheddable_fraction": 0.3},
            "pool": {"n_pods": 3},
            "shapes": [{"kind": "flash_crowd", "at_s": 1.0,
                        "ramp_s": 0.5, "hold_s": 1.5,
                        "magnitude": 4.0, "decay_s": 0.5}],
        }})
    judgment = judge_mod.judge(art, scenarios=(scn,))
    assert judgment["promote"] is True
    (row,) = judgment["scenarios"]
    assert row["passed"] and all(row["gates"].values())
    assert (row["learned"]["goodput_tokens_per_s"]
            > row["heuristic"]["goodput_tokens_per_s"])
    # The verdict is judged traffic-identical by construction.
    assert (row["heuristic"]["schedule_fingerprint"]
            == row["learned"]["schedule_fingerprint"])


def test_engine_config_heuristic_default_is_untouched():
    """With the flag off nothing changes: the storm engine's default
    EngineConfig carries the blend scorer and NO policy-weight override,
    so the pre-learn path stays bit-for-bit the production default."""
    from gie_tpu.storm.engine import EngineConfig

    cfg = EngineConfig()
    assert cfg.scorer == "blend"
    assert cfg.policy_weights == ()


# ------------------------------------------------- obs feeds (satellites)

def _filled_recorder(n=5):
    from gie_tpu.obs.recorder import FlightRecorder

    rec = FlightRecorder(size=16)
    for i in range(n):
        rec.append(_record(0.5, 0.5, 0.5, 10.0 + i))
    return rec


def test_dump_rotator_bounds_files_and_writes_loadable_envelopes(tmp_path):
    from gie_tpu.obs.recorder import DumpRotator

    rec = _filled_recorder()
    rot = DumpRotator(str(tmp_path), keep=3, name="rot")
    paths = [rot.rotate_once(recorder=rec) for _ in range(6)]
    assert all(p is not None for p in paths)
    kept = rot.rotation_files()
    assert [os.path.basename(p) for p in kept] == [
        "rot-00000003.json", "rot-00000004.json", "rot-00000005.json"]
    # Every rotation file is a standard dump envelope the trainer loads.
    fp, records = dataset_mod.load_dump(kept[-1])
    assert fp and len(records) == 5
    assert dataset_mod.build_dataset([(fp, records)]).features.shape[0] == 5


def test_dump_rotator_never_prunes_foreign_files(tmp_path):
    from gie_tpu.obs.recorder import DumpRotator

    foreign = tmp_path / "chaos-scenario-dump.json"
    foreign.write_text("{}")
    other = tmp_path / "other-00000000.json"
    other.write_text("{}")
    rot = DumpRotator(str(tmp_path), keep=1, name="rot")
    for _ in range(3):
        rot.rotate_once(recorder=_filled_recorder())
    assert foreign.exists() and other.exists()
    assert len(rot.rotation_files()) == 1


def test_dump_rotator_failure_paths(tmp_path):
    from gie_tpu.obs.recorder import DumpRotator

    with pytest.raises(ValueError, match="keep"):
        DumpRotator(str(tmp_path), keep=0)
    # No installed recorder -> no-op, never a raise.
    assert DumpRotator(str(tmp_path)).rotate_once(recorder=None) is None
    # Unwritable target (a file where the directory should be) -> None.
    blocker = tmp_path / "not-a-dir"
    blocker.write_text("x")
    assert DumpRotator(str(blocker)).rotate_once(
        recorder=_filled_recorder()) is None


def test_dump_rotator_lock_is_ranked_and_order_clean():
    """Satellite 5: the rotator's counter lock is in the declared
    hierarchy and behaves as a leaf — acquiring it under the obs
    tracer's lock (rank 91 -> 92, ascending) is clean, and the tracked
    run observes the pair (non-vacuous)."""
    from gie_tpu.lint.dynamic import LockTracker, default_ranks
    from gie_tpu.obs.recorder import DumpRotator

    ranks = default_ranks()
    rot_name = "gie_tpu.obs.recorder.DumpRotator._lock"
    tracer_name = "gie_tpu.obs.trace.Tracer._lock"
    assert ranks[rot_name] > ranks[tracer_name]

    tracker = LockTracker(ranks=ranks)
    rot = DumpRotator("/tmp/unused-gie-learn", keep=1)
    tracker.wrap(rot, "_lock", rot_name)

    class _Outer:
        _lock = threading.Lock()

    outer = _Outer()
    tracker.wrap(outer, "_lock", tracer_name)
    with outer._lock:
        rot._next_seq()
    tracker.assert_consistent()
    assert (tracer_name, rot_name) in tracker.observed()


def test_obs_dump_cli_writes_envelope(tmp_path, monkeypatch):
    import gie_tpu.obs.__main__ as obs_cli

    records = _filled_recorder().snapshot()
    monkeypatch.setattr(
        obs_cli, "_fetch_picks", lambda *a, **kw: records)
    assert obs_cli.main(["dump", "--out", str(tmp_path)]) == 0
    (written,) = list(tmp_path.iterdir())
    assert written.name.startswith("harvest-")
    fp, loaded = dataset_mod.load_dump(str(written))
    assert len(loaded) == 5 and fp


def test_obs_dump_cli_reports_harvest_failure(tmp_path, monkeypatch, capsys):
    import gie_tpu.obs.__main__ as obs_cli

    def boom(*a, **kw):
        raise OSError("connection refused")

    monkeypatch.setattr(obs_cli, "_fetch_picks", boom)
    assert obs_cli.main(["dump", "--out", str(tmp_path)]) == 1
    assert "harvest failed" in capsys.readouterr().err
    assert not list(tmp_path.iterdir())


# -------------------------------------------------- runtime flag surface

def _opts(**kw):
    from gie_tpu.runtime.options import Options

    return Options(pool_name="p", **kw)


def test_scorer_flag_validation():
    _opts().validate()
    _opts(scorer="learned", policy_artifact="x.json").validate()
    with pytest.raises(ValueError, match="policy-artifact"):
        _opts(scorer="learned").validate()
    with pytest.raises(ValueError, match="scorer learned"):
        _opts(policy_artifact="x.json").validate()
    with pytest.raises(ValueError, match="scorer"):
        _opts(scorer="sum").validate()


def test_obs_dump_rotation_flag_validation():
    _opts(obs_dump_interval_s=30.0).validate()
    with pytest.raises(ValueError, match="flight recorder"):
        _opts(obs_dump_interval_s=30.0, obs=False).validate()
    with pytest.raises(ValueError):
        _opts(obs_dump_interval_s=-1.0).validate()
    with pytest.raises(ValueError):
        _opts(obs_dump_interval_s=30.0, obs_dump_keep=0).validate()


def test_policy_info_metric_stamps_identity_labels():
    from prometheus_client import generate_latest

    from gie_tpu.runtime import metrics

    art = _valid_artifact()
    metrics.set_policy_info(
        "learned", {"queue": 0.25, "kv_cache": 3.0}, artifact=art)
    text = generate_latest(metrics.REGISTRY).decode()
    line = next(l for l in text.splitlines()
                if l.startswith("gie_policy_info{")
                and 'scorer="learned"' in l)
    assert art["checksum"] in line
    assert 'weights="kv_cache=3,queue=0.25"' in line
    assert 'artifact_schema="gie-learn-policy/1"' in line
