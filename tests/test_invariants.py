"""Randomized invariant sweep over the scheduling cycle.

Property-style coverage across pickers and configs: whatever the inputs,
the cycle must uphold the protocol contracts — picks only within the
eligibility mask, consistent status/index pairing, no invalid-slot leaks,
assumed load non-negative, distinct fallback entries.
"""

import functools

import jax
import numpy as np
import pytest

from gie_tpu.sched import constants as C
from gie_tpu.sched.profile import ProfileConfig, scheduling_cycle
from gie_tpu.sched.types import SchedState, Weights
from gie_tpu.utils.testing import make_endpoints, make_requests


@pytest.mark.parametrize("picker", ["topk", "random", "sinkhorn"])
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_cycle_invariants_random_inputs(picker, seed):
    rng = np.random.default_rng(seed)
    m = int(rng.integers(1, 40))
    n = int(rng.integers(1, 50))
    eps = make_endpoints(
        m,
        queue=rng.integers(0, 200, m).tolist(),
        kv=rng.uniform(0, 1.0, m).tolist(),
        max_lora=float(rng.integers(0, 6)),
    )
    subsets = []
    for _ in range(n):
        r = rng.uniform()
        if r < 0.3:
            subsets.append(None)  # no hint
        elif r < 0.4:
            subsets.append([int(x) for x in rng.integers(400, 500, 2)])  # dead
        else:
            k = int(rng.integers(1, m + 1))
            subsets.append(rng.choice(m, size=k, replace=False).tolist())
    prompts = [bytes(rng.integers(65, 90, int(rng.integers(0, 2000)),
                                  dtype=np.uint8)) for _ in range(n)]
    reqs = make_requests(
        n,
        prompts=prompts,
        subset=subsets,
        lora_id=rng.integers(-1, 5, n).tolist(),
        criticality=rng.integers(0, 3, n).tolist(),
    )
    cfg = ProfileConfig(picker=picker, queue_limit=float(rng.integers(10, 300)))
    fn = jax.jit(functools.partial(scheduling_cycle, cfg=cfg, predictor_fn=None))
    result, state = fn(
        SchedState.init(), reqs, eps, Weights.default(),
        jax.random.PRNGKey(seed), None,
    )
    indices = np.asarray(result.indices)
    status = np.asarray(result.status)
    mask = np.asarray(reqs.subset_mask) & np.asarray(eps.valid)[None, :]

    for i in range(n):
        if status[i] == C.Status.OK:
            assert indices[i, 0] >= 0
            for j in indices[i]:
                if j >= 0:
                    assert mask[i, j], f"pick {j} outside mask for row {i}"
            picked = [int(x) for x in indices[i] if x >= 0]
            assert len(set(picked)) == len(picked), "duplicate fallbacks"
        else:
            assert (indices[i] == -1).all(), "non-OK rows must carry no picks"
        if subsets[i] is not None and all(s >= 400 for s in subsets[i]):
            assert status[i] != C.Status.OK, "dead subset must not be OK"
    assert (np.asarray(state.assumed_load) >= 0).all()
    assert int(state.tick) == 1
