"""Tracing spans: histogram observation + TRACE-level logs."""

import io
import json

from gie_tpu.runtime.logging import Logger, set_verbosity
from gie_tpu.runtime.metrics import REGISTRY
from gie_tpu.runtime.tracing import span


def _count(name: str) -> float:
    for metric in REGISTRY.collect():
        for sample in metric.samples:
            if (sample.name == "gie_span_seconds_count"
                    and sample.labels.get("span") == name):
                return sample.value
    return 0.0


def test_span_records_histogram_and_survives_exceptions():
    before = _count("unit.test")
    with span("unit.test", attr="x"):
        pass
    try:
        with span("unit.test"):
            raise RuntimeError("boom")
    except RuntimeError:
        pass
    assert _count("unit.test") == before + 2  # recorded even on raise


def test_span_trace_log_emission(monkeypatch):
    import gie_tpu.runtime.tracing as tracing

    buf = io.StringIO()
    monkeypatch.setattr(tracing, "_log", Logger("trace", stream=buf))
    set_verbosity(5)
    try:
        with span("logged.section", candidates=3):
            pass
    finally:
        set_verbosity(2)
    line = json.loads(buf.getvalue().splitlines()[-1])
    assert line["name"] == "logged.section"
    assert line["candidates"] == 3
    assert line["seconds"] >= 0
