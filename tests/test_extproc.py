"""ext-proc protocol tests.

In-memory stream tier mirrors reference handlers tests
(mockProcessServer pattern, server_test.go:33-59; subset variants,
request_test.go:50-551); the gRPC tier runs the real service end-to-end over
localhost — the transport the data plane actually uses.
"""

import threading

import grpc
import pytest
from google.protobuf import struct_pb2

from gie_tpu.datastore import Datastore
from gie_tpu.datastore.objects import EndpointPool
from gie_tpu.extproc import (
    RoundRobinPicker,
    StreamingServer,
    metadata as mdkeys,
    pb,
)
from gie_tpu.extproc.envoy import (
    BODY_BYTE_LIMIT,
    build_chunked_body_responses,
    extract_header_value,
)
from gie_tpu.extproc.server import ExtProcError, MAX_REQUEST_BODY_SIZE
from tests.test_datastore import make_pod  # reuse builders


POOL = EndpointPool(selector={"app": "vllm"}, target_ports=[8000], namespace="default")


class FakeStream:
    """Scripted bidirectional stream (reference mockProcessServer)."""

    def __init__(self, messages):
        self.messages = list(messages)
        self.sent = []

    def recv(self):
        return self.messages.pop(0) if self.messages else None

    def send(self, resp):
        self.sent.append(resp)


def make_ds(n=3):
    ds = Datastore()
    ds.pool_set(POOL)
    for i in range(n):
        ds.pod_update_or_add(make_pod(name=f"p{i}", ip=f"10.0.0.{i}"))
    return ds


def headers_msg(headers=None, end_of_stream=True, metadata_struct=None):
    hm = pb.HeaderMap()
    for k, v in (headers or {}).items():
        hm.headers.append(pb.HeaderValue(key=k, raw_value=v.encode()))
    req = pb.ProcessingRequest(
        request_headers=pb.HttpHeaders(headers=hm, end_of_stream=end_of_stream)
    )
    if metadata_struct:
        for ns, fields in metadata_struct.items():
            st = struct_pb2.Struct()
            for fk, fv in fields.items():
                if isinstance(fv, list):
                    st.fields[fk].list_value.values.extend(
                        [struct_pb2.Value(string_value=x) for x in fv]
                    )
                else:
                    st.fields[fk].string_value = fv
            req.metadata_context.filter_metadata[ns].CopyFrom(st)
    return req


def body_msg(data=b"", end_of_stream=True):
    return pb.ProcessingRequest(
        request_body=pb.HttpBody(body=data, end_of_stream=end_of_stream)
    )


def dest_header(resp):
    mut = resp.request_headers.response.header_mutation
    for opt in mut.set_headers:
        if opt.header.key == mdkeys.DESTINATION_ENDPOINT_KEY:
            return opt.header.raw_value.decode()
    return None


def test_headers_only_request_round_robin():
    srv = StreamingServer(make_ds(), RoundRobinPicker())
    stream = FakeStream([headers_msg()])
    srv.process(stream)
    assert len(stream.sent) == 1
    resp = stream.sent[0]
    dest = dest_header(resp)
    assert dest in {f"10.0.0.{i}:8000" for i in range(3)}
    assert resp.request_headers.response.clear_route_cache
    # Dual signal: dynamic metadata must agree with the header (004 README:46-82).
    md = resp.dynamic_metadata.fields[mdkeys.DESTINATION_ENDPOINT_NAMESPACE]
    assert (
        md.struct_value.fields[mdkeys.DESTINATION_ENDPOINT_KEY].string_value == dest
    )


def test_round_robin_rotates():
    srv = StreamingServer(make_ds(), RoundRobinPicker())
    seen = set()
    for _ in range(6):
        stream = FakeStream([headers_msg()])
        srv.process(stream)
        seen.add(dest_header(stream.sent[0]))
    assert len(seen) == 3


def test_body_defers_headers_response():
    """Headers without end_of_stream defer the pick until the body completes
    (reference server.go:183,200-258)."""
    srv = StreamingServer(make_ds(), RoundRobinPicker())
    stream = FakeStream(
        [
            headers_msg(end_of_stream=False),
            body_msg(b"part1", end_of_stream=False),
            body_msg(b"part2", end_of_stream=True),
        ]
    )
    srv.process(stream)
    kinds = [r.WhichOneof("response") for r in stream.sent]
    assert kinds == ["request_headers", "request_body"]
    assert dest_header(stream.sent[0]) is not None


def test_subset_metadata_string_form():
    srv = StreamingServer(make_ds(), RoundRobinPicker())
    md = {
        mdkeys.SUBSET_FILTER_NAMESPACE: {
            mdkeys.SUBSET_FILTER_KEY: " 10.0.0.1 , 10.0.0.2"
        }
    }
    for _ in range(4):
        stream = FakeStream([headers_msg(metadata_struct=md)])
        srv.process(stream)
        assert dest_header(stream.sent[0]).rsplit(":", 1)[0] in {
            "10.0.0.1",
            "10.0.0.2",
        }


def test_subset_metadata_array_form():
    srv = StreamingServer(make_ds(), RoundRobinPicker())
    md = {
        mdkeys.SUBSET_FILTER_NAMESPACE: {
            mdkeys.SUBSET_FILTER_KEY: ["10.0.0.0", "10.0.0.2"]
        }
    }
    for _ in range(4):
        stream = FakeStream([headers_msg(metadata_struct=md)])
        srv.process(stream)
        assert dest_header(stream.sent[0]).rsplit(":", 1)[0] in {
            "10.0.0.0",
            "10.0.0.2",
        }


def test_subset_with_ports_filters_exact_endpoint():
    ds = Datastore()
    ds.pool_set(
        EndpointPool(selector={"app": "vllm"}, target_ports=[8000, 8002],
                     namespace="default")
    )
    ds.pod_update_or_add(make_pod(name="p0", ip="10.0.0.0"))
    srv = StreamingServer(ds, RoundRobinPicker())
    md = {
        mdkeys.SUBSET_FILTER_NAMESPACE: {mdkeys.SUBSET_FILTER_KEY: "10.0.0.0:8002"}
    }
    stream = FakeStream([headers_msg(metadata_struct=md)])
    srv.process(stream)
    assert dest_header(stream.sent[0]) == "10.0.0.0:8002"


def test_strict_empty_subset_unavailable():
    """Explicit subset matching nothing -> UNAVAILABLE, never fail-open
    (reference request.go:130-133)."""
    srv = StreamingServer(make_ds(), RoundRobinPicker())
    md = {mdkeys.SUBSET_FILTER_NAMESPACE: {mdkeys.SUBSET_FILTER_KEY: "10.9.9.9"}}
    with pytest.raises(ExtProcError) as ei:
        srv.process(FakeStream([headers_msg(metadata_struct=md)]))
    assert ei.value.code == grpc.StatusCode.UNAVAILABLE


def test_no_pods_unavailable():
    ds = Datastore()
    ds.pool_set(POOL)
    srv = StreamingServer(ds, RoundRobinPicker())
    with pytest.raises(ExtProcError) as ei:
        srv.process(FakeStream([headers_msg()]))
    assert ei.value.code == grpc.StatusCode.UNAVAILABLE
    assert "no pods available" in ei.value.message


def test_test_steering_header_priority():
    """test-epp-endpoint-selection overrides metadata subsetting
    (reference request.go:84-97)."""
    srv = StreamingServer(make_ds(), RoundRobinPicker())
    md = {mdkeys.SUBSET_FILTER_NAMESPACE: {mdkeys.SUBSET_FILTER_KEY: "10.0.0.1"}}
    stream = FakeStream(
        [
            headers_msg(
                headers={mdkeys.TEST_ENDPOINT_SELECTION_HEADER: "10.0.0.2"},
                metadata_struct=md,
            )
        ]
    )
    srv.process(stream)
    assert dest_header(stream.sent[0]) == "10.0.0.2:8000"


def test_body_size_cap():
    srv = StreamingServer(make_ds(), RoundRobinPicker())
    big = b"x" * (MAX_REQUEST_BODY_SIZE // 2 + 1)
    with pytest.raises(ExtProcError) as ei:
        srv.process(
            FakeStream(
                [
                    headers_msg(end_of_stream=False),
                    body_msg(big, end_of_stream=False),
                    body_msg(big, end_of_stream=False),
                ]
            )
        )
    assert ei.value.code == grpc.StatusCode.RESOURCE_EXHAUSTED


def test_response_headers_served_endpoint_echo():
    """Served-endpoint feedback loop (004 README:84-101; reference
    response.go:30-92)."""
    served = []
    srv = StreamingServer(
        make_ds(), RoundRobinPicker(), on_served=lambda ep, ctx: served.append(ep)
    )
    req = pb.ProcessingRequest(response_headers=pb.HttpHeaders())
    st = struct_pb2.Struct()
    st.fields[mdkeys.DESTINATION_ENDPOINT_SERVED_KEY].string_value = "10.0.0.1:8000"
    req.metadata_context.filter_metadata[
        mdkeys.DESTINATION_ENDPOINT_NAMESPACE
    ].CopyFrom(st)
    stream = FakeStream([headers_msg(), req])
    srv.process(stream)
    assert served == ["10.0.0.1:8000"]
    mut = stream.sent[1].response_headers.response.header_mutation
    echoed = {
        o.header.key: o.header.raw_value.decode() for o in mut.set_headers
    }
    assert echoed[mdkeys.CONFORMANCE_TEST_RESULT_HEADER] == "10.0.0.1:8000"
    assert echoed[mdkeys.WENT_INTO_RESP_HEADERS] == "true"


def test_response_body_passthrough():
    srv = StreamingServer(make_ds(), RoundRobinPicker())
    stream = FakeStream(
        [headers_msg(), pb.ProcessingRequest(response_body=pb.HttpBody())]
    )
    srv.process(stream)
    assert stream.sent[1].WhichOneof("response") == "response_body"


def test_chunked_body_responses():
    """62 KB chunk framing (reference chunking.go:26-74)."""
    body = b"a" * (BODY_BYTE_LIMIT * 2 + 100)
    responses = build_chunked_body_responses(body, request_path=True)
    assert len(responses) == 3
    sizes = [len(r.request_body.response.body_mutation.body) for r in responses]
    assert sizes == [BODY_BYTE_LIMIT, BODY_BYTE_LIMIT, 100]
    assert all(
        r.request_body.response.status == pb.CommonResponse.CONTINUE_AND_REPLACE
        for r in responses
    )


# ---------------------------------------------------------------------------
# Real gRPC transport
# ---------------------------------------------------------------------------


def test_grpc_end_to_end():
    from concurrent import futures

    from gie_tpu.extproc.service import SERVICE_NAME, add_extproc_service

    srv = StreamingServer(make_ds(), RoundRobinPicker())
    gserver = grpc.server(futures.ThreadPoolExecutor(max_workers=4))
    add_extproc_service(gserver, srv)
    port = gserver.add_insecure_port("127.0.0.1:0")
    gserver.start()
    try:
        channel = grpc.insecure_channel(f"127.0.0.1:{port}")
        method = channel.stream_stream(
            f"/{SERVICE_NAME}/Process",
            request_serializer=pb.ProcessingRequest.SerializeToString,
            response_deserializer=pb.ProcessingResponse.FromString,
        )
        responses = list(method(iter([headers_msg()])))
        assert len(responses) == 1
        assert dest_header(responses[0])

        # Error path: strict empty subset -> UNAVAILABLE over the wire.
        md = {
            mdkeys.SUBSET_FILTER_NAMESPACE: {mdkeys.SUBSET_FILTER_KEY: "1.2.3.4"}
        }
        with pytest.raises(grpc.RpcError) as ei:
            list(method(iter([headers_msg(metadata_struct=md)])))
        assert ei.value.code() == grpc.StatusCode.UNAVAILABLE
        channel.close()
    finally:
        gserver.stop(0)
