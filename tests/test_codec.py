"""gRPC transcoding tests (proposal 2162): framing, JSON<->protobuf, SSE,
and the full ext-proc transcode choreography for h2c pools."""

import json

import pytest

import gie_tpu.extproc  # noqa: F401 — installs the pb path hook
from gie_tpu.extproc.pb import generate_pb2

from gie_tpu.datastore import Datastore
from gie_tpu.datastore.objects import EndpointPool
from gie_tpu.extproc import RoundRobinPicker, StreamingServer, codec, pb
from tests.test_datastore import make_pod
from tests.test_extproc import FakeStream, body_msg, dest_header, headers_msg


def test_frame_roundtrip():
    msgs = [b"alpha", b"", b"x" * 1000]
    framed = b"".join(codec.frame(m) for m in msgs)
    assert list(codec.iter_frames(framed)) == msgs


def test_incremental_decoder_split_boundaries():
    msgs = [b"one", b"twotwo", b"three33"]
    framed = b"".join(codec.frame(m) for m in msgs)
    dec = codec.FrameDecoder()
    out = []
    # Feed in awkward 4-byte chunks crossing every boundary.
    for i in range(0, len(framed), 4):
        out.extend(dec.feed(framed[i : i + 4]))
    assert out == msgs


def test_json_to_generate_request_completion_and_chat():
    framed, stream, model_name = codec.json_to_generate_request(
        json.dumps({"model": "m1", "prompt": "hello", "max_tokens": 7,
                    "stream": True}).encode()
    )
    assert stream and model_name == "m1"
    (payload,) = list(codec.iter_frames(framed))
    req = generate_pb2.GenerateRequest.FromString(payload)
    assert (req.model, req.prompt, req.max_tokens, req.stream) == (
        "m1", "hello", 7, True)

    framed, _, _ = codec.json_to_generate_request(
        json.dumps({"model": "m2", "messages": [
            {"role": "system", "content": "be terse"},
            {"role": "user", "content": "hi"},
        ]}).encode()
    )
    (payload,) = list(codec.iter_frames(framed))
    req = generate_pb2.GenerateRequest.FromString(payload)
    assert "system: be terse" in req.prompt and "user: hi" in req.prompt

    assert codec.json_to_generate_request(b"not json") == (None, False, "")
    assert codec.json_to_generate_request(b'{"no": "prompt"}') == (None, False, "")
    # Untranscodable field values refuse cleanly instead of raising.
    assert codec.json_to_generate_request(
        json.dumps({"prompt": "x", "max_tokens": -1}).encode()
    ) == (None, False, "")
    assert codec.json_to_generate_request(
        json.dumps({"prompt": "x", "temperature": [1]}).encode()
    ) == (None, False, "")


def test_responses_to_json_merges_chunks():
    frames = b"".join(
        codec.frame(generate_pb2.GenerateResponse(text=t).SerializeToString())
        for t in ("Hel", "lo")
    ) + codec.frame(
        generate_pb2.GenerateResponse(
            text="!", finished=True, finish_reason="stop",
            completion_tokens=3).SerializeToString()
    )
    out = json.loads(codec.generate_responses_to_json(frames, model="m"))
    assert out["choices"][0]["text"] == "Hello!"
    assert out["choices"][0]["finish_reason"] == "stop"
    assert out["usage"]["completion_tokens"] == 3


def test_sse_conversion_emits_done():
    payload = generate_pb2.GenerateResponse(
        text="tok", finished=True, finish_reason="stop").SerializeToString()
    sse = codec.generate_response_to_sse(payload).decode()
    assert sse.startswith("data: {")
    assert sse.endswith("data: [DONE]\n\n")


def make_h2c_server():
    ds = Datastore()
    ds.pool_set(EndpointPool({"app": "x"}, [8000], "default",
                             app_protocol="kubernetes.io/h2c"))
    ds.pod_update_or_add(make_pod(name="p0", labels={"app": "x"}, ip="10.0.0.1"))
    return StreamingServer(ds, RoundRobinPicker()), ds


def test_extproc_transcodes_request_body_for_h2c_pool():
    srv, _ = make_h2c_server()
    body = json.dumps({"model": "m", "prompt": "hi", "stream": False}).encode()
    stream = FakeStream([
        headers_msg(end_of_stream=False), body_msg(body, end_of_stream=True),
    ])
    srv.process(stream)
    hdr, body_resp = stream.sent
    muts = {o.header.key: o.header.raw_value.decode()
            for o in hdr.request_headers.response.header_mutation.set_headers}
    assert muts["content-type"] == codec.GRPC_CONTENT_TYPE
    assert muts["te"] == "trailers"
    common = body_resp.request_body.response
    assert common.status == common.CONTINUE_AND_REPLACE
    (payload,) = list(codec.iter_frames(common.body_mutation.body))
    assert generate_pb2.GenerateRequest.FromString(payload).prompt == "hi"


def test_extproc_response_stream_to_sse():
    srv, _ = make_h2c_server()
    req_body = json.dumps({"model": "m", "prompt": "hi", "stream": True}).encode()
    chunk1 = codec.frame(
        generate_pb2.GenerateResponse(text="Hel").SerializeToString())
    chunk2 = codec.frame(generate_pb2.GenerateResponse(
        text="lo", finished=True, finish_reason="stop").SerializeToString())
    stream = FakeStream([
        headers_msg(end_of_stream=False),
        body_msg(req_body, end_of_stream=True),
        pb.ProcessingRequest(response_body=pb.HttpBody(body=chunk1)),
        pb.ProcessingRequest(
            response_body=pb.HttpBody(body=chunk2, end_of_stream=True)),
    ])
    srv.process(stream)
    sse1 = stream.sent[2].response_body.response.body_mutation.body.decode()
    sse2 = stream.sent[3].response_body.response.body_mutation.body.decode()
    assert '"text": "Hel"' in sse1
    assert sse2.endswith("data: [DONE]\n\n")


def test_extproc_response_buffered_to_json():
    srv, _ = make_h2c_server()
    req_body = json.dumps({"model": "m", "prompt": "hi", "stream": False}).encode()
    frames = codec.frame(
        generate_pb2.GenerateResponse(text="Hi ").SerializeToString()
    ) + codec.frame(generate_pb2.GenerateResponse(
        text="there", finished=True, finish_reason="stop").SerializeToString())
    stream = FakeStream([
        headers_msg(end_of_stream=False),
        body_msg(req_body, end_of_stream=True),
        pb.ProcessingRequest(
            response_body=pb.HttpBody(body=frames, end_of_stream=True)),
    ])
    srv.process(stream)
    out = json.loads(
        stream.sent[2].response_body.response.body_mutation.body)
    assert out["choices"][0]["text"] == "Hi there"


def test_grpc_in_client_passes_through_unframed():
    """gRPC-in clients (content-type application/grpc) are not transcoded."""
    srv, _ = make_h2c_server()
    grpc_body = codec.frame(
        generate_pb2.GenerateRequest(model="m", prompt="x").SerializeToString())
    stream = FakeStream([
        headers_msg(headers={"content-type": "application/grpc"},
                    end_of_stream=False),
        body_msg(grpc_body, end_of_stream=True),
    ])
    srv.process(stream)
    body_resp = stream.sent[1].request_body.response
    # No CONTINUE_AND_REPLACE mutation: the body passes through as-is.
    assert body_resp.status == pb.CommonResponse.CONTINUE
    assert dest_header(stream.sent[0]) is not None


def test_http_pool_not_transcoded():
    ds = Datastore()
    ds.pool_set(EndpointPool({"app": "x"}, [8000], "default"))  # http default
    ds.pod_update_or_add(make_pod(name="p0", labels={"app": "x"}, ip="10.0.0.1"))
    srv = StreamingServer(ds, RoundRobinPicker())
    body = json.dumps({"model": "m", "prompt": "hi"}).encode()
    stream = FakeStream([
        headers_msg(end_of_stream=False), body_msg(body, end_of_stream=True),
    ])
    srv.process(stream)
    assert stream.sent[1].request_body.response.status == pb.CommonResponse.CONTINUE


def test_compressed_frame_emits_clean_error():
    """An undecodable response frame yields a clean error in the promised
    format (the client already saw SSE/JSON response headers) and never
    mixes raw gRPC bytes into the stream."""
    srv, _ = make_h2c_server()
    req_body = json.dumps({"model": "m", "prompt": "hi", "stream": True}).encode()
    compressed = b"\x01" + (5).to_bytes(4, "big") + b"zzzzz"
    stream = FakeStream([
        headers_msg(end_of_stream=False),
        body_msg(req_body, end_of_stream=True),
        pb.ProcessingRequest(response_body=pb.HttpBody(body=compressed)),
        pb.ProcessingRequest(
            response_body=pb.HttpBody(body=b"more raw", end_of_stream=True)),
    ])
    srv.process(stream)
    err = stream.sent[2].response_body.response
    assert err.status == pb.CommonResponse.CONTINUE_AND_REPLACE
    out = err.body_mutation.body.decode()
    assert '"error"' in out and out.endswith("data: [DONE]\n\n")
    # Subsequent chunks are blanked, never passed through raw.
    tail = stream.sent[3].response_body.response
    assert tail.status == pb.CommonResponse.CONTINUE_AND_REPLACE
    assert tail.body_mutation.body == b""


def test_transcoded_response_content_type_rewritten():
    srv, _ = make_h2c_server()
    req_body = json.dumps({"model": "m", "prompt": "hi", "stream": True}).encode()
    stream = FakeStream([
        headers_msg(end_of_stream=False),
        body_msg(req_body, end_of_stream=True),
        pb.ProcessingRequest(response_headers=pb.HttpHeaders()),
    ])
    srv.process(stream)
    mut = {o.header.key: o.header.raw_value.decode()
           for o in stream.sent[2].response_headers.response
           .header_mutation.set_headers}
    assert mut["content-type"] == "text/event-stream"


def test_truncated_final_frame_reports_error():
    """A partial trailing frame at end_of_stream must not produce a silent
    200 with missing text."""
    srv, _ = make_h2c_server()
    req_body = json.dumps({"model": "m", "prompt": "hi", "stream": False}).encode()
    good = codec.frame(
        generate_pb2.GenerateResponse(text="partial").SerializeToString())
    truncated = good + b"\x00" + (99).to_bytes(4, "big") + b"short"
    stream = FakeStream([
        headers_msg(end_of_stream=False),
        body_msg(req_body, end_of_stream=True),
        pb.ProcessingRequest(
            response_body=pb.HttpBody(body=truncated, end_of_stream=True)),
    ])
    srv.process(stream)
    out = json.loads(stream.sent[2].response_body.response.body_mutation.body)
    assert "error" in out
    assert "truncated" in out["error"]["message"]


def test_model_echoed_in_transcoded_response():
    srv, _ = make_h2c_server()
    req_body = json.dumps({"model": "llama-3", "prompt": "hi",
                           "stream": False}).encode()
    frames = codec.frame(generate_pb2.GenerateResponse(
        text="ok", finished=True, finish_reason="stop").SerializeToString())
    stream = FakeStream([
        headers_msg(end_of_stream=False),
        body_msg(req_body, end_of_stream=True),
        pb.ProcessingRequest(
            response_body=pb.HttpBody(body=frames, end_of_stream=True)),
    ])
    srv.process(stream)
    out = json.loads(stream.sent[2].response_body.response.body_mutation.body)
    assert out["model"] == "llama-3"


def test_chat_content_parts_fold_to_text():
    framed, _, _ = codec.json_to_generate_request(json.dumps({
        "model": "m",
        "messages": [{"role": "user", "content": [
            {"type": "text", "text": "part one "},
            {"type": "image_url", "image_url": {"url": "http://x"}},
            {"type": "text", "text": "part two"},
        ]}],
    }).encode())
    (payload,) = list(codec.iter_frames(framed))
    req = generate_pb2.GenerateRequest.FromString(payload)
    assert req.prompt == "user: part one part two"


def test_non_string_text_part_ignored():
    """Client-controlled garbage in content parts must not crash the
    request path."""
    framed, _, _ = codec.json_to_generate_request(json.dumps({
        "messages": [{"role": "u", "content": [
            {"type": "text", "text": 123},
            {"type": "text", "text": "ok"},
        ]}],
    }).encode())
    (payload,) = list(codec.iter_frames(framed))
    assert generate_pb2.GenerateRequest.FromString(payload).prompt == "u: ok"
