"""Scheduler-core unit tests.

Coverage model follows the reference's handler/scorer unit tier (SURVEY.md
section 4: subset parsing variants, strict-subset 503, concurrency) mapped to
the batched pipeline: masks, scorer ordering, fallback lists, status codes,
assumed-load dynamics, prefix affinity.
"""

import numpy as np
import pytest

from gie_tpu.sched import (
    Criticality,
    ProfileConfig,
    Scheduler,
    Status,
    Weights,
)
from gie_tpu.utils.testing import make_endpoints, make_requests


def test_picks_least_loaded_endpoint():
    """Default blend prefers the endpoint with least queue + kv pressure
    (reference default least-kv-cache scorer, BASELINE configs[0])."""
    sched = Scheduler()
    eps = make_endpoints(4, queue=[10, 0, 10, 10], kv=[0.9, 0.1, 0.9, 0.9])
    reqs = make_requests(3)
    res = sched.pick(reqs, eps)
    assert res.status.tolist() == [Status.OK] * 3
    assert all(res.indices[i, 0] == 1 for i in range(3))


def test_strict_subset_empty_gives_503():
    """An explicit empty/unsatisfiable subset hint must 503, never fall back
    to the full pool (reference request.go:130-133, 004 README:28-44)."""
    sched = Scheduler()
    eps = make_endpoints(2, queue=[0, 0])
    # Request 0 restricted to invalid slot 7 (not a valid endpoint);
    # request 1 unrestricted.
    reqs = make_requests(2, subset=[[7], None])
    res = sched.pick(reqs, eps)
    assert res.status[0] == Status.NO_CAPACITY
    assert (res.indices[0] == -1).all()
    assert res.status[1] == Status.OK


def test_subset_honored_when_nonempty():
    sched = Scheduler()
    # Slot 1 is far better, but request is pinned to slot 0 and 3.
    eps = make_endpoints(4, queue=[50, 0, 0, 40], kv=[0.5, 0.0, 0.0, 0.4])
    reqs = make_requests(1, subset=[[0, 3]])
    res = sched.pick(reqs, eps)
    assert res.status[0] == Status.OK
    assert res.indices[0, 0] == 3  # better of the two allowed
    picked = set(int(i) for i in res.indices[0] if i >= 0)
    assert picked <= {0, 3}


def test_no_endpoints_gives_503():
    sched = Scheduler()
    eps = make_endpoints(0)
    reqs = make_requests(2)
    res = sched.pick(reqs, eps)
    assert res.status.tolist() == [Status.NO_CAPACITY] * 2


def test_sheddable_gets_429_when_saturated_critical_does_not():
    """Saturation sheds SHEDDABLE traffic with 429 while CRITICAL bypasses
    the filter (004 README:77-80; 006 README saturation semantics)."""
    cfg = ProfileConfig(queue_limit=10, kv_limit=0.9)
    sched = Scheduler(cfg)
    eps = make_endpoints(2, queue=[50, 60], kv=[0.99, 0.99])
    reqs = make_requests(
        2, criticality=[Criticality.SHEDDABLE, Criticality.CRITICAL]
    )
    res = sched.pick(reqs, eps)
    assert res.status[0] == Status.SHED
    assert (res.indices[0] == -1).all()
    assert res.status[1] == Status.OK
    assert res.indices[1, 0] >= 0


def test_fallback_list_ordered_and_distinct():
    """Ordered fallback list semantics (004 README:50-82)."""
    sched = Scheduler()
    eps = make_endpoints(8, queue=[0, 1, 2, 3, 4, 5, 6, 7])
    reqs = make_requests(1)
    res = sched.pick(reqs, eps)
    idx = [int(i) for i in res.indices[0]]
    assert len(set(idx)) == len(idx)
    scores = [float(s) for s in res.scores[0]]
    assert scores == sorted(scores, reverse=True)
    assert idx[0] == 0  # least queue wins


def test_lora_affinity_prefers_resident_adapter():
    sched = Scheduler(weights=Weights.default())
    eps = make_endpoints(
        3,
        queue=[0, 0, 0],
        max_lora=4,
        lora_active=[[7], [], []],
    )
    reqs = make_requests(1, lora_id=[7])
    res = sched.pick(reqs, eps)
    assert res.indices[0, 0] == 0


def test_lora_capacity_filter_blocks_full_endpoints():
    """Endpoint at max_lora with the adapter absent is ineligible."""
    sched = Scheduler()
    eps = make_endpoints(
        2,
        queue=[0, 50],
        max_lora=1,
        lora_active=[[3], []],  # slot 0 full with adapter 3; slot 1 has room
    )
    reqs = make_requests(1, lora_id=[9])
    res = sched.pick(reqs, eps)
    # Slot 0 is better on queue but full for adapter 9 -> must pick 1.
    assert res.indices[0, 0] == 1


def test_assumed_load_spreads_consecutive_batches():
    """Assumed-load accounting must push later picks off the argmax endpoint
    before metrics refresh (006 README:156)."""
    cfg = ProfileConfig(load_decay=1.0, load_norm=4.0, enable_prefix=False)
    w = Weights.default().replace(assumed_load=np.float32(4.0))
    sched = Scheduler(cfg, weights=w)
    eps = make_endpoints(4, queue=[0, 0, 0, 0])
    seen = set()
    for _ in range(4):
        res = sched.pick(make_requests(8, prompt_len=[4096.0] * 8), eps)
        seen.update(int(i) for i in res.indices[:, 0])
    assert len(seen) >= 3  # load spread, not herded on one endpoint


def test_complete_feedback_releases_assumed_load():
    cfg = ProfileConfig(load_decay=1.0)
    sched = Scheduler(cfg)
    eps = make_endpoints(2, queue=[0, 0])
    res = sched.pick(make_requests(4, prompt_len=[2048.0] * 4), eps)
    load_after_pick = sched.snapshot_assumed_load()
    assert load_after_pick.sum() > 0
    slots = np.asarray(res.indices[:, 0])
    sched.complete(slots, np.full(slots.shape, 1.0, np.float32))
    assert sched.snapshot_assumed_load().sum() < load_after_pick.sum()


def test_prefix_affinity_routes_repeat_prefix_to_same_endpoint():
    """Prefix-cache-aware scheduling (0602 README:95-129): a request whose
    prompt shares a long prefix with an earlier one should land on the same
    endpoint even if another endpoint is slightly less loaded."""
    cfg = ProfileConfig(load_decay=0.0)
    w = Weights.default().replace(prefix=np.float32(3.0))
    sched = Scheduler(cfg, weights=w)
    eps = make_endpoints(4, queue=[1, 1, 1, 1])
    sys_prompt = b"You are a helpful assistant. " * 40  # >> chunk size
    res1 = sched.pick(make_requests(1, prompts=[sys_prompt + b"Q1"]), eps)
    first = int(res1.indices[0, 0])
    # Make every other endpoint slightly better on queue.
    queue = [0.5] * 4
    queue[first] = 1.0
    eps2 = make_endpoints(4, queue=queue)
    res2 = sched.pick(make_requests(1, prompts=[sys_prompt + b"Q2"]), eps2)
    assert int(res2.indices[0, 0]) == first


def test_prefix_no_false_match_for_different_prompts():
    cfg = ProfileConfig(load_decay=0.0)
    w = Weights.default().replace(prefix=np.float32(3.0))
    sched = Scheduler(cfg, weights=w)
    eps = make_endpoints(4, queue=[3, 3, 3, 0])
    res1 = sched.pick(make_requests(1, prompts=[b"A" * 4096]), eps)
    first = int(res1.indices[0, 0])
    assert first == 3
    # A totally different prompt should go to the least-loaded endpoint, not
    # chase the other prompt's cache.
    eps2 = make_endpoints(4, queue=[0, 3, 3, 3])
    res2 = sched.pick(make_requests(1, prompts=[b"B" * 4096]), eps2)
    assert int(res2.indices[0, 0]) == 0


def test_random_picker_spreads_and_respects_mask():
    cfg = ProfileConfig(picker="random", enable_prefix=False)
    sched = Scheduler(cfg)
    eps = make_endpoints(4, queue=[0, 0, 0, 50])
    reqs = make_requests(64, subset=[[0, 1, 2]] * 64)
    res = sched.pick(reqs, eps)
    picks = set(int(i) for i in res.indices[:, 0])
    assert picks <= {0, 1, 2}
    assert len(picks) >= 2  # sampling spreads across equals


def test_invalid_rows_padded_batches():
    """Bucket padding must not leak picks into padded rows."""
    sched = Scheduler()
    eps = make_endpoints(2, queue=[0, 0])
    reqs = make_requests(3)  # pads to bucket 8
    res = sched.pick(reqs, eps)
    assert res.indices.shape[0] == 3  # trimmed back to caller's batch


def test_large_batch_256x_all_ok():
    sched = Scheduler()
    eps = make_endpoints(64, queue=list(np.arange(64) % 7))
    reqs = make_requests(200)
    res = sched.pick(reqs, eps)
    assert (np.asarray(res.status) == Status.OK).all()
    assert (np.asarray(res.indices[:, 0]) >= 0).all()


def test_pick_async_bit_identical_to_sync_across_m_boundary():
    """ISSUE 1 async-dispatch equivalence: pick_async + materialize must be
    BIT-identical to the synchronous pick for the same wave sequence —
    including an M-bucket grow (64 -> 256) and shrink (256 -> 64) mid-
    sequence — and the assumed-load accounting must track exactly. The
    async path changes WHEN the host waits, never what the cycle computes."""
    rng = np.random.default_rng(42)
    waves = []
    for step, m_slots in enumerate([64, 64, 256, 256, 64]):
        m_live = 8 if m_slots == 64 else 96
        eps = make_endpoints(
            m_live,
            queue=rng.integers(0, 40, m_live).tolist(),
            kv=rng.uniform(0, 0.9, m_live).tolist(),
            m_slots=m_slots)
        reqs = make_requests(
            12,
            prompts=[b"SYS %d | " % (i % 3) * 30 + b"q%d.%d" % (step, i)
                     for i in range(12)],
            m_slots=m_slots)
        waves.append((reqs, eps))

    sync = Scheduler(seed=9)
    pipelined = Scheduler(seed=9)
    for reqs, eps in waves:
        ra = sync.pick(reqs, eps)
        pw = pipelined.pick_async(reqs, eps, snapshot_load=True)
        rb = pw.materialize()
        np.testing.assert_array_equal(
            np.asarray(ra.indices), np.asarray(rb.indices))
        np.testing.assert_array_equal(
            np.asarray(ra.status), np.asarray(rb.status))
        np.testing.assert_array_equal(
            np.asarray(ra.scores), np.asarray(rb.scores))
        # The PendingWave's device-copy snapshot is the live post-cycle
        # state (it must survive the next cycle's buffer donation), and
        # both schedulers' accounting tracks bit-for-bit.
        np.testing.assert_array_equal(
            pw.materialize_load(), pipelined.snapshot_assumed_load())
        np.testing.assert_array_equal(
            sync.snapshot_assumed_load(), pipelined.snapshot_assumed_load())


def test_pick_async_back_to_back_preserves_cycle_order():
    """Two waves dispatched WITHOUT materializing between them must see
    each other's state updates in order (cycle k+1 queues behind cycle k
    via the donated state dependency) — materializing late changes
    nothing about the state sequence."""
    serial = Scheduler(ProfileConfig(load_decay=1.0))
    deferred = Scheduler(ProfileConfig(load_decay=1.0))
    eps = make_endpoints(4, queue=[0, 0, 0, 0])
    w1 = make_requests(8, prompt_len=[4096.0] * 8)
    w2 = make_requests(8, prompt_len=[1024.0] * 8)
    r1 = serial.pick(w1, eps)
    r2 = serial.pick(w2, eps)
    p1 = deferred.pick_async(w1, eps)
    p2 = deferred.pick_async(w2, eps)   # dispatched before p1 materializes
    np.testing.assert_array_equal(
        np.asarray(r1.indices), np.asarray(p1.materialize().indices))
    np.testing.assert_array_equal(
        np.asarray(r2.indices), np.asarray(p2.materialize().indices))
    np.testing.assert_array_equal(
        serial.snapshot_assumed_load(), deferred.snapshot_assumed_load())


def test_concurrent_picks_thread_safe():
    """Analogue of the reference datastore concurrency tests
    (datastore_test.go:61,867): concurrent picks + completes must not race or
    deadlock."""
    import threading

    sched = Scheduler()
    eps = make_endpoints(8, queue=[0] * 8)
    errs = []

    def worker():
        try:
            for _ in range(5):
                res = sched.pick(make_requests(4), eps)
                sched.complete(
                    np.asarray(res.indices[:, 0]), np.ones((4,), np.float32)
                )
        except Exception as e:  # pragma: no cover
            errs.append(e)

    threads = [threading.Thread(target=worker) for _ in range(8)]
    [t.start() for t in threads]
    [t.join() for t in threads]
    assert not errs


def test_evict_endpoint_clears_prefix_affinity():
    """A dead pod's slot must not attract prefix-affine traffic after
    eviction (datastore PodDelete analogue)."""
    cfg = ProfileConfig(load_decay=0.0)
    w = Weights.default().replace(prefix=np.float32(3.0))
    sched = Scheduler(cfg, weights=w)
    eps = make_endpoints(4, queue=[1, 1, 1, 1])
    prompt = b"shared prefix " * 100
    res1 = sched.pick(make_requests(1, prompts=[prompt + b"a"]), eps)
    home = int(res1.indices[0, 0])
    sched.evict_endpoint(home)
    queue = [0.0] * 4
    queue[home] = 0.0
    other_best = (home + 1) % 4
    queue2 = [1.0] * 4
    queue2[other_best] = 0.0
    res2 = sched.pick(make_requests(1, prompts=[prompt + b"b"]), make_endpoints(4, queue=queue2))
    assert int(res2.indices[0, 0]) == other_best


def test_standard_degrades_best_effort_when_all_saturated():
    """STANDARD traffic must not 503 on a fully saturated pool — it degrades
    to best-effort while SHEDDABLE sheds (004 README:77-80)."""
    cfg = ProfileConfig(queue_limit=10, kv_limit=0.9)
    sched = Scheduler(cfg)
    eps = make_endpoints(3, queue=[50, 40, 60], kv=[0.99, 0.95, 0.99])
    res = sched.pick(make_requests(1, criticality=[Criticality.STANDARD]), eps)
    assert res.status[0] == Status.OK
    assert res.indices[0, 0] == 1  # least loaded of the saturated set


def test_shed_disabled_sheddable_degrades_like_standard():
    cfg = ProfileConfig(queue_limit=10, kv_limit=0.9, shed_sheddable=False)
    sched = Scheduler(cfg)
    eps = make_endpoints(2, queue=[50, 40], kv=[0.99, 0.95])
    res = sched.pick(make_requests(1, criticality=[Criticality.SHEDDABLE]), eps)
    assert res.status[0] == Status.OK
    assert res.indices[0, 0] == 1


def test_sinkhorn_picker_spreads_wave_under_capacity():
    """OT picker must not herd a uniform wave onto one endpoint (the
    failure mode of deterministic argmax within a cycle)."""
    import collections

    cfg = ProfileConfig(picker="sinkhorn", enable_prefix=False)
    sched = Scheduler(cfg)
    eps = make_endpoints(4, queue=[0, 0, 0, 0])
    res = sched.pick(make_requests(64), eps)
    counts = collections.Counter(int(i) for i in np.asarray(res.indices[:, 0]))
    assert len(counts) == 4
    assert max(counts.values()) < 40  # no single endpoint takes the wave


def test_sinkhorn_respects_mask_and_status():
    cfg = ProfileConfig(picker="sinkhorn", enable_prefix=False)
    sched = Scheduler(cfg)
    eps = make_endpoints(4, queue=[0, 0, 0, 0])
    reqs = make_requests(8, subset=[[1, 2]] * 7 + [[400]])
    res = sched.pick(reqs, eps)
    assert set(int(i) for i in np.asarray(res.indices[:7, 0])) <= {1, 2}
    assert res.status[7] == Status.NO_CAPACITY


def test_sinkhorn_biases_toward_higher_capacity():
    """Loaded endpoints get proportionally less of the wave."""
    import collections

    cfg = ProfileConfig(picker="sinkhorn", enable_prefix=False,
                        queue_norm=16.0)
    sched = Scheduler(cfg)
    eps = make_endpoints(2, queue=[15, 0])
    res = sched.pick(make_requests(64), eps)
    counts = collections.Counter(int(i) for i in np.asarray(res.indices[:, 0]))
    assert counts[1] > counts[0] * 2


def test_sinkhorn_padded_wave_still_spreads():
    """Regression: a small wave padded up to a bucket must not inflate the
    capacity scale (padded rows carry no transport mass)."""
    import collections

    cfg = ProfileConfig(picker="sinkhorn", enable_prefix=False)
    sched = Scheduler(cfg)
    eps = make_endpoints(4, queue=[0, 0, 0, 0])
    res = sched.pick(make_requests(9), eps)  # pads to bucket 64
    counts = collections.Counter(int(i) for i in np.asarray(res.indices[:, 0]))
    assert max(counts.values()) <= 5
    assert len(counts) >= 3


def test_pallas_fused_topk_matches_default_path():
    """Pallas-kernel pick path (interpret mode on CPU) must agree with the
    default path wherever scores are untied; statuses must match exactly."""
    cfg_ref = ProfileConfig(enable_prefix=False)
    cfg_pl = ProfileConfig(enable_prefix=False, use_pallas_topk=True)
    eps = make_endpoints(8, queue=[0, 3, 7, 1, 9, 2, 5, 4])
    reqs = make_requests(8, subset=[[0, 1, 2, 3, 4, 5, 6, 7]] * 7 + [[400]])
    r_ref = Scheduler(cfg_ref).pick(reqs, eps)
    r_pl = Scheduler(cfg_pl).pick(reqs, eps)
    # Distinct queue depths -> untied scores -> identical ordering.
    assert (np.asarray(r_ref.indices) == np.asarray(r_pl.indices)).all()
    assert (np.asarray(r_ref.status) == np.asarray(r_pl.status)).all()


def test_pallas_fused_topk_parity_wide_bucket_full_columns():
    """Same parity at the 256-slot M bucket with the full column set
    (session + LoRA live): the kernel blends (stacked, wvec) itself, so
    a column-count or width assumption that drifted from build_stages
    would only surface at the wider shape."""
    cfg_ref = ProfileConfig(enable_prefix=False)
    cfg_pl = ProfileConfig(enable_prefix=False, use_pallas_topk=True)
    rng = np.random.default_rng(11)
    m = 64
    eps = make_endpoints(
        m, queue=rng.integers(0, 50, m).tolist(),
        kv=rng.uniform(0, 0.9, m).tolist(), max_lora=4, m_slots=256)
    reqs = make_requests(
        48,
        prompts=[b"SYS %d | " % (i % 5) * 30 + b"u%d" % i
                 for i in range(48)],
        lora_id=rng.integers(-1, 6, 48).tolist(),
        m_slots=256)
    r_ref = Scheduler(cfg_ref).pick(reqs, eps)
    r_pl = Scheduler(cfg_pl).pick(reqs, eps)
    assert (np.asarray(r_ref.status) == np.asarray(r_pl.status)).all()
    # Primary picks agree wherever the winner is untied; with random
    # queue/kv draws ties are measure-zero, so require full agreement.
    assert (np.asarray(r_ref.indices[:, 0])
            == np.asarray(r_pl.indices[:, 0])).all()


def test_sinkhorn_warm_start_inert_on_idle_fleet():
    """The utilization gate (round 5): on an IDLE fleet the carried
    column duals must not change picks — caps bind even at idle (they
    are normalized to wave mass), so an ungated carry would split
    sessions off warm endpoints for no latency benefit. A LOADED fleet
    must actually use the prior (v_out differs from a cold solve)."""
    import jax
    import jax.numpy as jnp

    from gie_tpu.sched.sinkhorn import sinkhorn_picker

    rng = np.random.default_rng(3)
    n, m_live = 32, 6

    def pick(eps, v0):
        scores = jnp.asarray(
            rng.uniform(0, 1, (n, eps.valid.shape[0])).astype(np.float32))
        mask = jnp.broadcast_to(eps.valid[None, :], scores.shape)
        res, v_out = sinkhorn_picker(
            scores, mask, jnp.zeros((n,), bool), jnp.ones((n,), bool),
            eps, jax.random.PRNGKey(0),
            queue_limit=128.0, tau=0.02, iters=8, rounding_temp=0.05,
            v0=v0)
        return np.asarray(res.indices), np.asarray(v_out)

    # Idle fleet: zero queues, zero kv -> utilization ~0 -> v0^0 = ones.
    idle = make_endpoints(m_live, queue=[0] * m_live, kv=[0.0] * m_live,
                          m_slots=64)
    biased = jnp.ones((64,), jnp.float32).at[0].set(1e-3)
    rng = np.random.default_rng(3)
    cold_idx, _ = pick(idle, None)
    rng = np.random.default_rng(3)
    warm_idx, _ = pick(idle, biased)
    assert (cold_idx == warm_idx).all(), (
        "carried duals changed picks on an idle fleet — the utilization "
        "gate is not neutralizing the prior")

    # Loaded fleet: deep queues / high kv -> the prior must be live
    # (the solve starts from a genuinely different v_init).
    loaded = make_endpoints(
        m_live, queue=[120] * m_live, kv=[0.9] * m_live, m_slots=64)
    rng = np.random.default_rng(3)
    _, v_cold = pick(loaded, None)
    rng = np.random.default_rng(3)
    _, v_warm = pick(loaded, biased)
    assert not np.allclose(v_cold, v_warm), (
        "loaded-fleet solve ignored the carried duals entirely")


def test_pallas_sinkhorn_matches_reference_path():
    """The VMEM-resident sinkhorn loop (interpret mode on CPU) must agree
    with the lax.scan reference to float tolerance — identical picks on
    untied inputs, matching statuses — INCLUDING the warm start: the
    kernel consumes v_init and returns the same evolved column duals the
    dual-form path carries (ADVICE r5 #2)."""
    import jax

    from gie_tpu.ops.fused_sinkhorn import fused_sinkhorn_plan
    from gie_tpu.sched.sinkhorn import capacities

    rng = np.random.default_rng(0)
    eps = make_endpoints(8, queue=rng.integers(0, 40, 8).tolist())
    cap = capacities(eps, 64.0, queue_limit=128.0)
    m = int(cap.shape[0])  # the endpoint batch's M bucket
    k = np.where(rng.uniform(0, 1, (64, m)) > 0.5,
                 rng.uniform(0, 1, (64, m)), 0.0).astype(np.float32)
    k[:, 8:] = 0.0

    import jax.numpy as jnp

    def ref(kk, cap, v_init):
        # The dual-form iteration from sinkhorn.py: two matvecs carrying
        # (u, v), seeded with the warm-start duals.
        def body(carry, _):
            u, v = carry
            r = kk @ v
            u = jnp.where(r > 0, 1.0 / r, u)
            col = v * (u @ kk)
            v = v * jnp.where(col > cap, cap / jnp.maximum(col, 1e-9), 1.0)
            return (u, v), None

        (u, v), _ = jax.lax.scan(
            body, (jnp.ones(kk.shape[:1], jnp.float32), v_init),
            None, length=8)
        p = kk * u[:, None] * v[None, :]
        row = jnp.sum(p, axis=1, keepdims=True)
        return jnp.where(row > 0, p / row, p), v

    for v_init in (
        np.ones((m,), np.float32),                        # cold start
        rng.uniform(0.05, 1.0, m).astype(np.float32),     # warm duals
    ):
        plan_pl, v_pl = fused_sinkhorn_plan(
            np.asarray(k), cap, jnp.asarray(v_init), iters=8,
            interpret=True)
        plan_ref, v_ref = ref(jnp.asarray(k), cap, jnp.asarray(v_init))
        np.testing.assert_allclose(
            np.asarray(plan_pl), np.asarray(plan_ref), atol=1e-5)
        np.testing.assert_allclose(
            np.asarray(v_pl), np.asarray(v_ref), atol=1e-5)

    cfg_a = ProfileConfig(picker="sinkhorn", enable_prefix=False)
    cfg_b = ProfileConfig(picker="sinkhorn", enable_prefix=False,
                          use_pallas_sinkhorn=True)
    reqs = make_requests(16)
    sched_a, sched_b = Scheduler(cfg_a, seed=7), Scheduler(cfg_b, seed=7)
    # TWO sequential waves: the second consumes the ot_v duals the first
    # wave carried, so this covers warm-start parity end to end (the old
    # single-pick assertion only ever compared cold solves).
    for _ in range(2):
        ra = sched_a.pick(reqs, eps)
        rb = sched_b.pick(reqs, eps)
        assert (np.asarray(ra.status) == np.asarray(rb.status)).all()
        assert (np.asarray(ra.indices) == np.asarray(rb.indices)).all()
    np.testing.assert_allclose(
        np.asarray(sched_a.state.ot_v), np.asarray(sched_b.state.ot_v),
        atol=1e-5)


def test_background_lattice_warm_removes_inline_stall():
    """warm_lattice_async compiles every N bucket of an (m, chunk_lanes)
    lattice off the dispatch path: a cold request-count bucket dispatched
    AFTER warmup completes must not take the inline first-use-compile
    stall (ROADMAP follow-up: the dispatcher blocked on first-use jit of
    new wave shapes)."""
    from gie_tpu.sched import constants as C

    sched = Scheduler(ProfileConfig(enable_prefix=False))
    t = sched.warm_lattice_async(64, C.MAX_CHUNKS)
    t.join(timeout=600)
    assert not t.is_alive(), "lattice warm thread did not finish"
    assert sched.warm_inline_compiles == 0

    eps = make_endpoints(4, queue=[0, 1, 2, 3], m_slots=64)
    # Three waves landing in three DIFFERENT cold N buckets: all were
    # pre-compiled by the warmer, so none may stall inline.
    for n in (1, 5, 60):
        res = sched.pick(make_requests(n, m_slots=64), eps)
        assert res.status.tolist() == [Status.OK] * n
    assert sched.warm_inline_compiles == 0

    # A shape OUTSIDE the warmed lattice still takes (and counts) the
    # inline path — the counter is the stall observability hook.
    eps256 = make_endpoints(4, queue=[0, 1, 2, 3], m_slots=256)
    sched.pick(make_requests(2, m_slots=256), eps256)
    assert sched.warm_inline_compiles == 1
