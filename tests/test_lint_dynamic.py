"""Dynamic lock-order confirmer (ISSUE 6): the instrumented Lock wrapper
records REAL acquisition orders under real traffic and asserts them
against the same lockorder.toml hierarchy the static analyzer enforces.
The integration test drives the actual ScrapeEngine/MetricsStore pair —
the one statically-proven nesting family — and additionally asserts the
nesting was OBSERVED, so the consistency check cannot pass vacuously."""

from __future__ import annotations

import threading
import time

import pytest

from gie_tpu.lint.dynamic import LockTracker, TrackedLock, default_ranks

ENGINE_LOCK = "gie_tpu.metricsio.engine.ScrapeEngine._lock"
STORE_LOCK = "gie_tpu.metricsio.store.MetricsStore._lock"


# --------------------------------------------------------------------------
# Tracker unit behavior
# --------------------------------------------------------------------------


class _Box:
    def __init__(self):
        self.a = threading.Lock()
        self.b = threading.Lock()


def test_tracker_records_inversion_and_order():
    tracker = LockTracker(ranks={"t.a": 10, "t.b": 20})
    box = _Box()
    tracker.wrap(box, "a", "t.a")
    tracker.wrap(box, "b", "t.b")

    with box.a:           # rank 10 then 20: correct
        with box.b:
            pass
    assert tracker.violations == []
    assert ("t.a", "t.b") in tracker.observed()

    with box.b:           # rank 20 then 10: inversion
        with box.a:
            pass
    assert len(tracker.violations) == 1
    v = tracker.violations[0]
    assert (v.outer, v.inner) == ("t.b", "t.a")
    with pytest.raises(AssertionError, match="rank inversion"):
        tracker.assert_consistent()


def test_tracker_is_per_thread():
    tracker = LockTracker(ranks={"t.a": 10, "t.b": 20})
    box = _Box()
    tracker.wrap(box, "a", "t.a")
    tracker.wrap(box, "b", "t.b")

    # Thread 1 holds b while thread 2 takes a: no nesting, no violation.
    barrier = threading.Barrier(2)

    def hold_b():
        with box.b:
            barrier.wait()
            barrier.wait()

    t = threading.Thread(target=hold_b)
    t.start()
    barrier.wait()
    with box.a:
        pass
    barrier.wait()
    t.join()
    tracker.assert_consistent()


def test_tracked_lock_delegates_condition_api():
    tracker = LockTracker(ranks={"t.c": 10})
    holder = type("H", (), {})()
    holder.c = threading.Condition()
    tracker.wrap(holder, "c", "t.c")
    assert isinstance(holder.c, TrackedLock)
    with holder.c:
        holder.c.wait(0.01)       # delegated through __getattr__
        holder.c.notify_all()
    tracker.assert_consistent()


def test_wrap_is_idempotent():
    tracker = LockTracker(ranks={"t.a": 10})
    box = _Box()
    first = tracker.wrap(box, "a", "t.a")
    assert tracker.wrap(box, "a", "t.a") is first


def test_default_ranks_load_the_repo_hierarchy():
    ranks = default_ranks()
    assert ranks[ENGINE_LOCK] < ranks[STORE_LOCK]  # engine wraps store


# --------------------------------------------------------------------------
# Integration: real engine/store traffic against the declared hierarchy
# --------------------------------------------------------------------------


def test_worker_pool_lifecycle_lock_stays_off_the_dispatch_path():
    """gie-wire: drive real streams through a 2-worker SO_REUSEPORT pool
    with the pool's lifecycle lock and the datastore lock tracked. The
    declared contract (lockorder.toml rank 18) is that the pool lock
    guards bind/start/stop only — so no nesting involving it may ever be
    observed, in either direction, while traffic flows."""
    import grpc

    from gie_tpu.extproc import pb
    from gie_tpu.extproc.server import StreamingServer
    from gie_tpu.extproc.workers import ExtProcWorkerPool
    from tests.test_extproc import RoundRobinPicker, make_ds

    POOL_LOCK = "gie_tpu.extproc.workers.ExtProcWorkerPool._lock"
    DS_LOCK = "gie_tpu.datastore.datastore.Datastore._lock"

    ds = make_ds()
    streaming = StreamingServer(ds, RoundRobinPicker(), fast_lane=True)
    pool = ExtProcWorkerPool(streaming, 2, wire=True)
    tracker = LockTracker(ranks=default_ranks())
    tracker.wrap(pool, "_lock", POOL_LOCK)
    tracker.wrap(ds, "_lock", DS_LOCK)

    port = pool.bind("127.0.0.1:0")
    pool.start()
    try:
        channel = grpc.insecure_channel(f"127.0.0.1:{port}")
        process = channel.stream_stream(
            "/envoy.service.ext_proc.v3.ExternalProcessor/Process",
            request_serializer=pb.ProcessingRequest.SerializeToString,
            response_deserializer=pb.ProcessingResponse.FromString)
        req = pb.ProcessingRequest()
        req.request_headers.headers.headers.add(
            key=":path", raw_value=b"/v1/completions")
        req.request_headers.end_of_stream = True
        for _ in range(10):
            assert len(list(process(iter([req])))) == 1
        channel.close()
    finally:
        pool.stop(grace=2.0).wait(5)

    tracker.assert_consistent()
    for outer, inner in tracker.observed():
        assert POOL_LOCK not in (outer, inner), (
            f"pool lifecycle lock nested with {outer!r}/{inner!r} — the "
            "accept/dispatch path must stay lock-free")


def test_engine_store_traffic_matches_declared_hierarchy():
    from gie_tpu.metricsio.engine import ScrapeEngine
    from gie_tpu.metricsio.mappings import BY_NAME
    from gie_tpu.metricsio.store import MetricsStore

    store = MetricsStore()
    payload = (
        b"vllm:num_requests_running 2.0\n"
        b"vllm:num_requests_waiting 1.0\n"
        b"vllm:gpu_cache_usage_perc 0.5\n"
    )
    engine = ScrapeEngine(
        store, interval_s=0.01, workers=2,
        fetcher=lambda url: payload)
    tracker = LockTracker(ranks=default_ranks())
    tracker.wrap(engine, "_lock", ENGINE_LOCK)
    tracker.wrap(store, "_lock", STORE_LOCK)
    mapping = BY_NAME["vllm"]
    try:
        for slot in range(8):
            engine.attach(slot, f"http://10.0.0.{slot}:9400/metrics",
                          mapping)
        deadline = time.monotonic() + 3.0
        # Control-plane reads interleave with shard sweeps, like the
        # runner's metrics exposition does.
        while time.monotonic() < deadline:
            store.pool_rows(list(range(8)))
            engine.staleness_seconds()
            if ((ENGINE_LOCK, STORE_LOCK) in tracker.observed()
                    and store.pool_rows([0])[0].sum() > 0):
                break
            time.sleep(0.02)
        engine.detach(3)
    finally:
        engine.close()

    tracker.assert_consistent()
    observed = tracker.observed()
    assert (ENGINE_LOCK, STORE_LOCK) in observed, (
        "engine->store nesting never observed — the integration drive "
        f"went vacuous (saw: {sorted(observed)})")
    assert (STORE_LOCK, ENGINE_LOCK) not in observed
