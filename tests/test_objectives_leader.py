"""InferenceObjective registry (proposal 1199) + leader election tests."""

import time

import numpy as np

from gie_tpu.api.objectives import (
    InferenceObjective,
    ObjectiveRegistry,
    band_for,
)
from gie_tpu.runtime.leader import LeaseFileElector
from gie_tpu.sched.constants import Criticality


def test_band_mapping():
    assert band_for(5) == Criticality.CRITICAL
    assert band_for(2) == Criticality.CRITICAL
    assert band_for(1) == Criticality.STANDARD
    assert band_for(0) == Criticality.SHEDDABLE
    assert band_for(-3) == Criticality.SHEDDABLE


def test_registry_resolves_names_and_literals():
    reg = ObjectiveRegistry()
    reg.apply(InferenceObjective(name="premium-chat", pool_ref="pool",
                                 criticality=3))
    reg.apply(InferenceObjective(name="batch-jobs", pool_ref="pool",
                                 criticality=0))
    assert reg.resolve_band("premium-chat") == Criticality.CRITICAL
    assert reg.resolve_band("batch-jobs") == Criticality.SHEDDABLE
    assert reg.resolve_band("critical") == Criticality.CRITICAL  # literal
    assert reg.resolve_band("unknown-name") is None
    assert reg.resolve_band("") is None
    reg.delete("default", "premium-chat")
    assert reg.resolve_band("premium-chat") is None


def test_objective_drives_scheduler_band():
    """A registered sheddable objective must shed under saturation through
    the batching picker."""
    from gie_tpu.datastore import Datastore
    from gie_tpu.datastore.objects import EndpointPool, Pod
    from gie_tpu.extproc import metadata as mdkeys
    from gie_tpu.extproc.server import PickRequest, ShedError
    from gie_tpu.metricsio import MetricsStore
    from gie_tpu.sched import Metric, ProfileConfig, Scheduler
    from gie_tpu.sched.batching import BatchingTPUPicker

    reg = ObjectiveRegistry()
    reg.apply(InferenceObjective(name="batch-tier", pool_ref="p",
                                 criticality=0))
    ds = Datastore()
    ds.pool_set(EndpointPool({"app": "x"}, [8000], "default"))
    ds.pod_update_or_add(Pod(name="p0", labels={"app": "x"}, ip="10.0.0.1"))
    ms = MetricsStore()
    ms.update(ds.endpoints()[0].slot,
              {Metric.QUEUE_DEPTH: 500, Metric.KV_CACHE_UTIL: 0.99})
    picker = BatchingTPUPicker(
        Scheduler(ProfileConfig(queue_limit=10, kv_limit=0.9)), ds, ms,
        max_wait_s=0.001,
    )
    picker.objective_registry = reg
    try:
        try:
            picker.pick(
                PickRequest(headers={mdkeys.OBJECTIVE_KEY: ["batch-tier"]},
                            body=b"x"),
                ds.endpoints(),
            )
            raise AssertionError("expected ShedError")
        except ShedError:
            pass
    finally:
        picker.close()


def test_leader_election_single_winner(tmp_path):
    lease = str(tmp_path / "epp.lease")
    a = LeaseFileElector(lease, lease_ttl_s=1.0, renew_interval_s=0.1)
    b = LeaseFileElector(lease, lease_ttl_s=1.0, renew_interval_s=0.1)
    a.start()
    time.sleep(0.4)
    b.start()
    time.sleep(0.5)
    try:
        assert a.is_leader()
        assert not b.is_leader()
        # Leader dies -> follower takes over within the TTL.
        a.stop()
        deadline = time.time() + 5
        while time.time() < deadline and not b.is_leader():
            time.sleep(0.1)
        assert b.is_leader()
    finally:
        a.stop()
        b.stop()


def test_health_liveness_vs_readiness():
    """004 README:103-137: liveness is unconditional; readiness gates."""
    import grpc

    from gie_tpu.runtime.health import (
        LIVENESS_SERVICE,
        READINESS_SERVICE,
        start_dedicated_health_server,
    )
    from gie_tpu.extproc.pb import health_pb2

    ready = {"v": False}
    server, port = start_dedicated_health_server(lambda: ready["v"], 0)
    try:
        ch = grpc.insecure_channel(f"127.0.0.1:{port}")
        check = ch.unary_unary(
            "/grpc.health.v1.Health/Check",
            request_serializer=health_pb2.HealthCheckRequest.SerializeToString,
            response_deserializer=health_pb2.HealthCheckResponse.FromString,
        )
        live = check(health_pb2.HealthCheckRequest(service=LIVENESS_SERVICE))
        assert live.status == health_pb2.HealthCheckResponse.SERVING
        rdy = check(health_pb2.HealthCheckRequest(service=READINESS_SERVICE))
        assert rdy.status == health_pb2.HealthCheckResponse.NOT_SERVING
        ready["v"] = True
        rdy = check(health_pb2.HealthCheckRequest(service=READINESS_SERVICE))
        assert rdy.status == health_pb2.HealthCheckResponse.SERVING
        ch.close()
    finally:
        server.stop(0)


def test_leader_takeover_atomic_under_contention(tmp_path):
    """Many contenders racing for an expired lease: at most one leader at
    any observation point."""
    lease = str(tmp_path / "contended.lease")
    # Seed an expired lease.
    with open(lease, "w") as f:
        f.write("dead-replica\n1.0")
    electors = [
        LeaseFileElector(lease, lease_ttl_s=2.0, renew_interval_s=0.05)
        for _ in range(6)
    ]
    for e in electors:
        e.start()
    try:
        time.sleep(1.0)
        for _ in range(10):
            leaders = [e for e in electors if e.is_leader()]
            assert len(leaders) <= 1
            time.sleep(0.05)
        assert any(e.is_leader() for e in electors)
    finally:
        for e in electors:
            e.stop()


def test_future_timestamp_lease_not_eternal(tmp_path):
    """A corrupt/future-dated lease must be taken over, not brick the
    deployment."""
    lease = str(tmp_path / "future.lease")
    with open(lease, "w") as f:
        f.write(f"ghost\n{time.time() + 9_999_999}")
    e = LeaseFileElector(lease, lease_ttl_s=1.0, renew_interval_s=0.1)
    e.start()
    try:
        deadline = time.time() + 5
        while time.time() < deadline and not e.is_leader():
            time.sleep(0.05)
        assert e.is_leader()
    finally:
        e.stop()


def test_stale_stop_does_not_unlink_new_leader(tmp_path):
    """A replica that lost leadership must not delete the new leader's
    lease on shutdown."""
    lease = str(tmp_path / "handoff.lease")
    a = LeaseFileElector(lease, lease_ttl_s=0.5, renew_interval_s=10.0)
    a.start()
    time.sleep(0.2)
    assert a.is_leader()
    # a's renew thread sleeps 10s; its lease expires at 0.5s and b takes it.
    b = LeaseFileElector(lease, lease_ttl_s=0.5, renew_interval_s=0.1)
    time.sleep(0.6)
    b.start()
    deadline = time.time() + 5
    while time.time() < deadline and not b.is_leader():
        time.sleep(0.05)
    assert b.is_leader()
    a.stop()  # stale leader flag; must NOT unlink b's lease
    time.sleep(0.3)
    assert b.is_leader()
    b.stop()


def test_objective_flag_roundtrip():
    """--objective NAME=CRITICALITY populates the runner registry."""
    import argparse

    from gie_tpu.runtime.options import Options

    parser = argparse.ArgumentParser()
    Options.add_flags(parser)
    args = parser.parse_args(
        ["--pool-name", "p", "--objective", "premium=3",
         "--objective", "batch=0"]
    )
    opts = Options.from_args(args)
    opts.validate()
    assert opts.objectives == ["premium=3", "batch=0"]
    import pytest as _pytest

    bad = parser.parse_args(["--pool-name", "p", "--objective", "nope"])
    with _pytest.raises(ValueError, match="NAME=CRITICALITY"):
        Options.from_args(bad).validate()


def test_two_process_leader_election(tmp_path):
    """Two REAL OS processes contend for one lease: every sampled instant
    has at most one leader, and a leader does emerge."""
    import os
    import subprocess
    import sys

    lease = str(tmp_path / "proc.lease")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    worker = os.path.join(repo, "tests", "leader_worker.py")
    procs = [
        subprocess.Popen([sys.executable, worker, lease, "3.0"], env=env,
                         stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                         text=True)
        for _ in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=60)
            assert p.returncode == 0, err[-1000:]
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    # Reconstruct the timeline at fine (0.1s) granularity: near-instant
    # samples must never show two leaders (a legitimate handover separated
    # by >= the sample period is fine), and a leader must emerge.
    samples = []
    for i, out in enumerate(outs):
        for line in out.splitlines():
            flag, ts = line.split()
            samples.append((round(float(ts.split("=")[1]), 1), i,
                            int(flag.split("=")[1])))
    by_bucket: dict = {}
    for bucket, proc_i, flag in samples:
        d = by_bucket.setdefault(bucket, {})
        d[proc_i] = max(d.get(proc_i, 0), flag)
    leaders_per_bucket = [sum(v.values()) for v in by_bucket.values()]
    assert max(leaders_per_bucket) <= 1, "two simultaneous leaders observed"
    assert any(leaders_per_bucket), "no leader ever elected"
