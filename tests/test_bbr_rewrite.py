"""BBR plugin chain + InferenceModelRewrite tests (proposals 1964 + 1816)."""

import json

import pytest

from gie_tpu.api.modelrewrite import (
    InferenceModelRewrite,
    ModelMatch,
    RewriteEngine,
    RewriteRule,
    TargetModel,
)
from gie_tpu.bbr import (
    MODEL_HEADER,
    ModelExtractorPlugin,
    ModelRewritePlugin,
    PluginChain,
)
from gie_tpu.extproc import RoundRobinPicker, StreamingServer, metadata as mdkeys
from tests.test_extproc import FakeStream, body_msg, headers_msg, make_ds


def test_model_extractor_sets_header():
    chain = PluginChain([ModelExtractorPlugin()])
    headers, mutated, parsed = chain.execute(
        json.dumps({"model": "llama-8b"}).encode())
    assert headers[MODEL_HEADER] == "llama-8b"
    assert mutated is None
    assert parsed == {"model": "llama-8b"}  # shared parse rides along


def test_chain_tolerates_non_json_body():
    chain = PluginChain([ModelExtractorPlugin()])
    headers, mutated, parsed = chain.execute(b"\x00\x01 not json")
    assert headers == {} and mutated is None and parsed is None


def make_engine():
    eng = RewriteEngine(seed=0)
    eng.apply(InferenceModelRewrite(
        name="rw-generic", pool_ref="pool",
        rules=[RewriteRule(targets=[TargetModel("fallback-model")])],
    ))
    eng.apply(InferenceModelRewrite(
        name="rw-exact", pool_ref="pool",
        rules=[RewriteRule(
            matches=[ModelMatch("gpt-fast")],
            targets=[TargetModel("llama-70b")],
        )],
    ))
    return eng


def test_exact_match_beats_generic_regardless_of_age():
    """1816 README: Exact precedence over generic even when the generic
    resource is older."""
    eng = make_engine()
    assert eng.resolve("pool", "gpt-fast") == "llama-70b"
    assert eng.resolve("pool", "anything-else") == "fallback-model"


def test_oldest_resource_wins_exact_ties():
    eng = RewriteEngine(seed=0)
    eng.apply(InferenceModelRewrite(
        name="older", pool_ref="pool",
        rules=[RewriteRule(matches=[ModelMatch("m")],
                           targets=[TargetModel("first")])],
    ))
    eng.apply(InferenceModelRewrite(
        name="newer", pool_ref="pool",
        rules=[RewriteRule(matches=[ModelMatch("m")],
                           targets=[TargetModel("second")])],
    ))
    assert eng.resolve("pool", "m") == "first"


def test_weighted_split_roughly_proportional():
    eng = RewriteEngine(seed=0)
    eng.apply(InferenceModelRewrite(
        name="split", pool_ref="pool",
        rules=[RewriteRule(
            matches=[ModelMatch("base")],
            targets=[TargetModel("a", weight=9), TargetModel("b", weight=1)],
        )],
    ))
    hits = {"a": 0, "b": 0}
    for _ in range(500):
        hits[eng.resolve("pool", "base")] += 1
    assert hits["a"] > hits["b"] * 3
    assert hits["b"] > 0


def test_rewrite_plugin_mutates_body_and_sets_headers():
    eng = make_engine()
    chain = PluginChain([
        ModelExtractorPlugin(),
        ModelRewritePlugin(eng, pool="pool"),
    ])
    headers, mutated, parsed = chain.execute(
        json.dumps({"model": "gpt-fast", "prompt": "hi"}).encode()
    )
    assert headers[MODEL_HEADER] == "llama-70b"
    assert headers[mdkeys.MODEL_NAME_REWRITE_KEY] == "llama-70b"
    assert json.loads(mutated)["model"] == "llama-70b"
    assert json.loads(mutated)["prompt"] == "hi"
    assert parsed["model"] == "llama-70b"  # post-mutation view


def test_bbr_through_extproc_server():
    """End to end: body arrives, BBR rewrites it, the data plane receives a
    CONTINUE_AND_REPLACE body mutation + the model headers."""
    eng = make_engine()
    srv = StreamingServer(
        make_ds(), RoundRobinPicker(),
        bbr_chain=PluginChain([
            ModelExtractorPlugin(), ModelRewritePlugin(eng, pool="pool"),
        ]),
    )
    body = json.dumps({"model": "gpt-fast", "prompt": "x"}).encode()
    stream = FakeStream([
        headers_msg(end_of_stream=False), body_msg(body, end_of_stream=True),
    ])
    srv.process(stream)
    hdr_resp, body_resp = stream.sent
    mut = {
        o.header.key: o.header.raw_value.decode()
        for o in hdr_resp.request_headers.response.header_mutation.set_headers
    }
    assert mut[MODEL_HEADER] == "llama-70b"
    common = body_resp.request_body.response
    assert common.status == common.CONTINUE_AND_REPLACE
    assert json.loads(common.body_mutation.body)["model"] == "llama-70b"


def test_upstream_rewrite_header_beats_extracted_model():
    """Regression: x-gateway-model-name-rewrite must win over the BBR
    extractor's raw body model (1816 rewrite > 1964 extraction)."""
    seen = {}

    class CapturePicker(RoundRobinPicker):
        def pick(self, req, candidates):
            seen["model"] = req.model
            return super().pick(req, candidates)

    srv = StreamingServer(
        make_ds(), CapturePicker(),
        bbr_chain=PluginChain([ModelExtractorPlugin()]),
    )
    stream = FakeStream([
        headers_msg(headers={mdkeys.MODEL_NAME_REWRITE_KEY: "llama-70b-ft"},
                    end_of_stream=False),
        body_msg(json.dumps({"model": "gpt-fast"}).encode(), end_of_stream=True),
    ])
    srv.process(stream)
    assert seen["model"] == "llama-70b-ft"
