"""Bounded flow-control queue: depth bound, criticality eviction, age
bound, and the overload starvation guarantee.

Reference: the EPP architecture proposal's flow-controller layer implies
bounded queues and an overload policy (reference docs/proposals/
0683-epp-architecture-proposal/README.md:64-66); VERDICT r02 Missing #4
asked for a queue-depth bound and a starvation guarantee under sustained
demand > capacity.
"""

import threading
import time
from collections import Counter

import grpc
import pytest

from gie_tpu.datastore import Datastore
from gie_tpu.datastore.objects import EndpointPool, Pod
from gie_tpu.extproc.server import ExtProcError, PickRequest, ShedError
from gie_tpu.extproc import metadata as mdkeys
from gie_tpu.metricsio import MetricsStore
from gie_tpu.runtime import metrics as own_metrics
from gie_tpu.sched import ProfileConfig, Scheduler
from gie_tpu.sched.batching import BatchingTPUPicker


def _stack(n_pods=2, **picker_kw):
    sched = Scheduler(ProfileConfig(load_decay=1.0))
    ms = MetricsStore()
    ds = Datastore(on_slot_reclaimed=lambda s: (sched.evict_endpoint(s),
                                                ms.remove(s)))
    ds.pool_set(EndpointPool({"app": "x"}, [8000], "default"))
    for i in range(n_pods):
        ds.pod_update_or_add(
            Pod(name=f"p{i}", labels={"app": "x"}, ip=f"10.9.0.{i + 1}")
        )
    picker = BatchingTPUPicker(sched, ds, ms, **picker_kw)
    return sched, ds, ms, picker


def _req(band: str = "", fairness: str = "") -> PickRequest:
    headers = {}
    if band:
        headers[mdkeys.OBJECTIVE_KEY] = [band]
    if fairness:
        headers[mdkeys.FLOW_FAIRNESS_ID_KEY] = [fairness]
    return PickRequest(headers=headers, body=b"prompt")


def _gauge_value() -> float:
    return own_metrics.QUEUE_DEPTH._value.get()


class TestDepthBound:
    def test_full_queue_sheds_equal_band_arrival(self):
        """With the collector wedged and the queue at its bound, a same-band
        arrival sheds immediately with 429 — it never waits."""
        sched, ds, ms, picker = _stack(
            queue_bound=2, max_wait_s=0.01, max_batch=1, pick_timeout_s=5)
        try:
            picker._run_batch = lambda batch: time.sleep(30) or []
            eps = ds.endpoints()

            # One pick drains into the wedged batch; two more fill the
            # pending queue to its bound.
            threads = [
                threading.Thread(target=_swallow, args=(picker, _req(), eps))
                for _ in range(3)
            ]
            for t in threads:
                t.start()
                time.sleep(0.1)
            time.sleep(0.3)
            t0 = time.perf_counter()
            with pytest.raises(ShedError):
                picker.pick(_req(), eps)
            assert time.perf_counter() - t0 < 0.5  # immediate, not queued
            assert _gauge_value() >= 2
        finally:
            picker.close()

    def test_critical_arrival_evicts_sheddable_waiter(self):
        """A CRITICAL arrival into a full queue evicts the newest
        SHEDDABLE waiter, which fails with 429."""
        sched, ds, ms, picker = _stack(
            queue_bound=2, max_wait_s=0.01, max_batch=1, pick_timeout_s=5)
        try:
            picker._run_batch = lambda batch: time.sleep(30) or []
            eps = ds.endpoints()
            shed_result = {}

            def sheddable_waiter():
                try:
                    shed_result["r"] = picker.pick(_req("sheddable"), eps)
                except ShedError as e:
                    shed_result["r"] = e

            # Filler drains into the wedged batch; then one standard + one
            # sheddable fill the pending queue to its bound of 2.
            t_fill = threading.Thread(
                target=lambda: _swallow(picker, _req(), eps))
            t_fill.start(); time.sleep(0.2)
            t_std = threading.Thread(
                target=lambda: _swallow(picker, _req("standard"), eps))
            t_shed = threading.Thread(target=sheddable_waiter)
            t_std.start(); time.sleep(0.1); t_shed.start(); time.sleep(0.3)

            # CRITICAL arrival: must be admitted (never shed while a
            # lower band waits) and the sheddable waiter must get 429.
            admitted = {}

            def critical():
                try:
                    admitted["r"] = picker.pick(_req("critical"), eps)
                except (ShedError, ExtProcError) as e:
                    admitted["r"] = e

            t_crit = threading.Thread(target=critical)
            t_crit.start()
            t_shed.join(timeout=5)
            assert isinstance(shed_result.get("r"), ShedError)
        finally:
            picker.close()

    def test_all_critical_queue_rejects_critical_arrival(self):
        """When the whole queue is CRITICAL, a CRITICAL arrival sheds —
        the bound is a bound, not a suggestion."""
        sched, ds, ms, picker = _stack(
            queue_bound=1, max_wait_s=0.01, max_batch=1, pick_timeout_s=5)
        try:
            picker._run_batch = lambda batch: time.sleep(30) or []
            eps = ds.endpoints()
            # Filler drains into the wedge; the second critical fills the
            # one-slot queue.
            for _ in range(2):
                t = threading.Thread(
                    target=lambda: _swallow(picker, _req("critical"), eps))
                t.start(); time.sleep(0.2)
            time.sleep(0.2)
            with pytest.raises(ShedError):
                picker.pick(_req("critical"), eps)
        finally:
            picker.close()


def _swallow(picker, req, eps):
    try:
        picker.pick(req, eps)
    except Exception:
        pass


def test_age_bound_sheds_stale_noncritical():
    """A non-critical pick that waited beyond queue_max_age_s sheds with
    429 when its wave drains."""
    sched, ds, ms, picker = _stack(
        queue_bound=0, max_wait_s=0.01, queue_max_age_s=0.2)
    try:
        # Wedge the collector long enough for the item to go stale, then
        # restore the real implementation so the next wave drains it.
        real = picker._run_batch
        picker._run_batch = lambda batch: (
            time.sleep(0.5),
            setattr(picker, "_run_batch", real),
            real(batch),
        )[-1]
        with pytest.raises(ShedError):
            picker.pick(_req("sheddable"), ds.endpoints())
    finally:
        picker.close()


def test_overload_starvation_guarantees():
    """Sustained demand > capacity: CRITICAL latency stays bounded, the
    queue depth stays at its bound, and the two sheddable tenants drain
    FAIRLY (neither is starved relative to the other).

    Capacity is constrained by max_batch=2 and a collector artificially
    slowed to ~25 waves/s; demand is ~3 tenants x continuous arrivals.
    """
    sched, ds, ms, picker = _stack(
        n_pods=4, queue_bound=8, max_wait_s=0.001, max_batch=2)
    try:
        real = picker._run_batch

        def slow_batch(batch):
            time.sleep(0.04)
            return real(batch)

        picker._run_batch = slow_batch
        eps = ds.endpoints()
        # Warm BOTH wave-size buckets (n=1 -> bucket 1, n=2 -> bucket 8)
        # before the measured window. The serial collector hid the second
        # shape's compile inside the first wave's multi-second device wait;
        # the pipelined dispatcher (ISSUE 1) drains faster and so meets
        # both shapes inside the window — and a one-time jit compile is
        # not the overload behavior this test measures.
        from gie_tpu.utils.testing import make_requests

        warm_eps = ms.endpoint_batch(ds.endpoints(), m_slots=64)
        for nw in (1, 2):
            wr = make_requests(nw, prompts=[b"prompt"] * nw, m_slots=64)
            wr = wr.replace(chunk_hashes=wr.chunk_hashes[:, :8])
            sched.pick(wr, warm_eps)
        stop = time.monotonic() + 3.0
        outcomes: Counter = Counter()
        crit_latencies = []
        lock = threading.Lock()

        def tenant(band, fid):
            while time.monotonic() < stop:
                t0 = time.perf_counter()
                try:
                    picker.pick(_req(band, fid), eps)
                    ok = f"ok-{fid or band}"
                except (ShedError, ExtProcError):
                    ok = f"shed-{fid or band}"
                dt = time.perf_counter() - t0
                with lock:
                    outcomes[ok] += 1
                    if band == "critical":
                        crit_latencies.append(dt)

        threads = [
            threading.Thread(target=tenant, args=("critical", "")),
            threading.Thread(target=tenant, args=("sheddable", "tenant-a")),
            threading.Thread(target=tenant, args=("sheddable", "tenant-b")),
        ]
        [t.start() for t in threads]
        [t.join(timeout=30) for t in threads]

        crit_ok = outcomes["ok-critical"]
        assert crit_ok >= 10, outcomes
        # CRITICAL latency bounded: drains first in every wave, so its
        # p95 stays within a few wave times even under overload.
        crit_latencies.sort()
        p95 = crit_latencies[int(0.95 * (len(crit_latencies) - 1))]
        assert p95 < 1.0, (p95, outcomes)
        # Sheddable tenants both make progress (scheduled or shed — they
        # always get an ANSWER; and both get comparable service).
        a_ok, b_ok = outcomes["ok-tenant-a"], outcomes["ok-tenant-b"]
        a_all = a_ok + outcomes["shed-tenant-a"]
        b_all = b_ok + outcomes["shed-tenant-b"]
        assert a_all > 0 and b_all > 0, outcomes
        total_ok = a_ok + b_ok
        if total_ok >= 10:
            # Fair interleave: neither tenant hogs the scheduled slots.
            assert min(a_ok, b_ok) / max(a_ok, b_ok) > 0.3, outcomes
        # The queue respected its bound throughout (gauge is set on every
        # enqueue/drain; spot-check the final value).
        assert _gauge_value() <= 8
    finally:
        picker.close()
