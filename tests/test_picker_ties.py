"""Exact-tie fallback enumeration (ADVICE r5 #4).

The threshold-descent _topk skips duplicate values: an exact float tie
used to gate the tied lane at NEG, silently shortening the protocol's
ordered fallback list. The iota*ulp tiebreak makes in-row values pairwise
distinct for the noise-based pickers; these tests drive DUPLICATE-endpoint
identical-score waves (noise forced to zero — the worst case the Gumbel
temperature normally makes merely improbable) and pin that every tied lane
now appears as its own fallback entry, while topk_picker's rotating
tie-break semantics are untouched.
"""

import jax
import jax.numpy as jnp
import numpy as np

from gie_tpu.sched import constants as C
from gie_tpu.sched.pickers import (
    _iota_tiebreak,
    topk_picker,
    weighted_random_picker,
)
from gie_tpu.sched.sinkhorn import sinkhorn_picker
from gie_tpu.utils.testing import make_endpoints


def _wave(n=4, m=16, live=8):
    scores = jnp.full((n, m), 0.625, jnp.float32)  # all-identical scores
    mask = jnp.zeros((n, m), bool).at[:, :live].set(True)
    shed = jnp.zeros((n,), bool)
    valid = jnp.ones((n,), bool)
    return scores, mask, shed, valid


def _assert_full_distinct_fallbacks(indices, live):
    idx = np.asarray(indices)
    assert (idx >= 0).all(), f"tied lanes dropped from fallback list: {idx}"
    for row in idx:
        assert len(set(row.tolist())) == C.FALLBACKS, row
        assert all(0 <= s < live for s in row), row


def test_random_picker_exact_ties_enumerate_fallbacks():
    scores, mask, shed, valid = _wave()
    # temperature=0 forces EXACT ties across the 8 duplicate lanes (the
    # picker's config validation forbids 0 precisely because of the old
    # truncation failure mode; calling the kernel directly is the test's
    # way to make the improbable collision certain).
    res = weighted_random_picker(
        scores, mask, shed, valid, jax.random.PRNGKey(0), temperature=0.0)
    _assert_full_distinct_fallbacks(res.indices, live=8)
    assert (np.asarray(res.status) == C.Status.OK).all()


def test_sinkhorn_picker_duplicate_endpoints_exact_ties():
    n, m, live = 4, 16, 8
    scores, mask, shed, valid = _wave(n, m, live)
    # Identical metrics on every duplicate endpoint -> identical transport
    # plan columns; rounding_temp=0 removes the symmetry-breaking noise.
    eps = make_endpoints(
        live, queue=[4.0] * live, kv=[0.2] * live, m_slots=m)
    res, _v = sinkhorn_picker(
        scores, mask, shed, valid, eps, jax.random.PRNGKey(1),
        queue_limit=128.0, tau=0.02, iters=8, rounding_temp=0.0)
    _assert_full_distinct_fallbacks(res.indices, live=live)


def test_topk_picker_rotation_semantics_unchanged():
    """topk_picker opts out of the iota nudge: its quantize-and-rotate
    tie-break already guarantees distinctness, and the round-robin
    ordering across cycles must stay exactly as before."""
    # live == m so the rotating lane priority wraps within the tied set.
    scores, mask, shed, valid = _wave(m=8, live=8)
    primaries = set()
    for rr in range(8):
        res = topk_picker(scores, mask, shed, valid, jnp.uint32(rr))
        idx = np.asarray(res.indices)
        assert (idx >= 0).all()
        primaries.add(int(idx[0, 0]))
    # The rotation spreads the primary pick across tied lanes over cycles.
    assert len(primaries) > 1


def test_iota_tiebreak_preserves_order_and_neg_lanes():
    """The nudge must (a) keep ineligible lanes at the exact NEG sentinel,
    (b) never reorder scores separated by more than M ulps, and (c) make
    exact ties strictly distinct — including in the log-domain magnitudes
    the sinkhorn path produces, where a fixed epsilon would be absorbed."""
    masked = jnp.asarray(
        [[0.9, 0.1, 0.1, C.NEG_SCORE],
         [-42.0, -42.0, -41.0, C.NEG_SCORE]], jnp.float32)
    mask = jnp.asarray(
        [[True, True, True, False], [True, True, True, False]])
    out = np.asarray(_iota_tiebreak(masked, mask))
    assert out[0, 3] == C.NEG_SCORE and out[1, 3] == C.NEG_SCORE
    assert out[0, 0] > out[0, 1] and out[0, 0] > out[0, 2]  # order kept
    assert out[0, 1] != out[0, 2]                           # tie broken
    assert out[1, 0] != out[1, 1], "log-domain tie must split (ulp-relative)"
    assert out[1, 2] > max(out[1, 0], out[1, 1])            # order kept


def test_iota_tiebreak_near_ulp_ties_stay_distinct():
    """The tiebreak must not MANUFACTURE collisions between distinct
    near-equal scores: lanes i<j exactly (j-i) ulps apart would collide
    under a naive bits+lane addition. The lane-field replacement keeps
    every such pair distinct, so both lanes survive into the fallback
    list."""
    base = np.float32(1.5)
    near = np.float32(base)
    for _ in range(2):
        near = np.float32(np.nextafter(near, np.float32(0.0)))
    # lane 0 = 1.5, lane 2 = 1.5 - 2 ulps: the historical collision case.
    masked = jnp.asarray([[base, 0.25, near, 0.25]], jnp.float32)
    mask = jnp.ones((1, 4), bool)
    out = np.asarray(_iota_tiebreak(masked, mask))
    assert len(set(out[0].tolist())) == 4, out
    res = weighted_random_picker(
        masked, mask, jnp.zeros((1,), bool), jnp.ones((1,), bool),
        jax.random.PRNGKey(0), temperature=0.0)
    idx = np.asarray(res.indices)[0]
    assert sorted(idx.tolist()) == [0, 1, 2, 3], idx
