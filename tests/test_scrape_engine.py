"""Multiplexed scrape-engine tests (ISSUE 4, docs/METRICSIO.md).

Covers: engine-vs-legacy MetricsStore row parity (byte-identical, incl.
the LoRA freshest-series rule), bounded thread count at 256 endpoints
(the tier-1 guard against thread-per-endpoint regressions), non-blocking
detach while a fetch is hung, adaptive backoff + snap-back, the batched
update_rows write path, and the real keep-alive HTTP path.
"""

import http.server
import threading
import time

import numpy as np
import pytest

from gie_tpu.metricsio import MetricsStore
from gie_tpu.metricsio.engine import ScrapeEngine
from gie_tpu.metricsio.mappings import SGLANG, VLLM
from gie_tpu.metricsio.scrape import Scraper, ThreadPerEndpointScraper
from gie_tpu.utils.lora import LoraRegistry

from tests.test_metricsio_sim import SGLANG_TEXT, VLLM_TEXT

# A second vLLM exposition with DIFFERENT freshest-series ordering (the
# older timestamp listed last) so parity covers the LoRA rule, plus
# adapter names overlapping VLLM_TEXT's to exercise registry id reuse.
VLLM_TEXT_2 = """\
vllm:num_requests_waiting 12
vllm:num_requests_running 1
vllm:kv_cache_usage_perc 0.91
vllm:cache_config_info{block_size="32",num_gpu_blocks="512"} 1
vllm:lora_requests_info{max_lora="8",running_lora_adapters="a2, zz",waiting_lora_adapters=""} 300.0
vllm:lora_requests_info{max_lora="8",running_lora_adapters="stale",waiting_lora_adapters="old"} 200.0
"""

FIXTURES = [
    ("http://10.1.0.1:8000/metrics", VLLM, VLLM_TEXT),
    ("http://10.1.0.2:8000/metrics", VLLM, VLLM_TEXT_2),
    ("http://10.1.0.3:8000/metrics", SGLANG, SGLANG_TEXT),
]


def _wait_rows(store: MetricsStore, slots, timeout=5.0) -> None:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if all(store._has_data[s] for s in slots):
            return
        time.sleep(0.005)
    missing = [s for s in slots if not store._has_data[s]]
    raise AssertionError(f"no scrape data for slots {missing}")


def test_engine_legacy_row_parity():
    """Engine and thread-per-endpoint scrapers must land BYTE-identical
    MetricsStore rows from the same expositions — including the LoRA
    freshest-series resolution and adapter id interning order."""
    texts = {url: text for url, _, text in FIXTURES}

    def scrape_with(make):
        store = MetricsStore()
        # Adapter ids are interned first-seen; concurrent scrapes make
        # that order arrival-dependent in BOTH implementations, so pin it
        # (production shares one registry across the process anyway).
        reg = LoraRegistry()
        for name in ("a1", "a2", "a3", "zz", "stale", "old"):
            reg.id_for(name)
        sc = make(store, reg, lambda url: texts[url])
        for slot, (url, mapping, _) in enumerate(FIXTURES):
            sc.attach(slot, url, mapping)
        _wait_rows(store, range(len(FIXTURES)))
        rows = store._metrics[: len(FIXTURES)].copy()
        act = store._lora_active[: len(FIXTURES)].copy()
        wait = store._lora_waiting[: len(FIXTURES)].copy()
        sc.close()
        return rows, act, wait

    e_rows, e_act, e_wait = scrape_with(
        lambda st, reg, f: ScrapeEngine(
            st, lora=reg, interval_s=0.01, fetcher=f, workers=2))
    l_rows, l_act, l_wait = scrape_with(
        lambda st, reg, f: ThreadPerEndpointScraper(
            st, lora=reg, interval_s=0.01, fetcher=f))

    assert e_rows.tobytes() == l_rows.tobytes()
    assert e_act.tobytes() == l_act.tobytes()
    assert e_wait.tobytes() == l_wait.tobytes()
    # Sanity: the fixtures actually landed values (not all-zeros parity).
    assert e_rows.any() and (e_act >= 0).any()


def test_scale_256_endpoints_bounded_threads_and_staleness():
    """256 endpoints on the 50 ms fast-poll cadence: thread count stays at
    workers + constant (NOT O(endpoints)), and p99 row staleness holds
    within 3x the interval (ISSUE 4 acceptance). Two measurement windows,
    best taken — this container's CPU is bistable under load (see
    test_soak's rate-gate note)."""
    interval = 0.05
    times: dict[int, list] = {}
    tlock = threading.Lock()

    class RecStore(MetricsStore):
        def update_rows(self, rows, now=None):
            super().update_rows(rows, now)
            t = time.monotonic()
            with tlock:
                for row in rows:
                    times.setdefault(row[0], []).append(t)

    before = threading.active_count()
    store = RecStore()
    eng = ScrapeEngine(
        store, interval_s=interval, fetcher=lambda url: VLLM_TEXT.encode())
    assert eng.workers <= 8
    for slot in range(256):
        eng.attach(slot, f"http://10.2.{slot // 250}.{slot % 250}:8000/m",
                   VLLM)
    # O(shards), not O(endpoints): the guard that motivated the engine.
    assert threading.active_count() - before <= eng.workers + 2
    try:
        _wait_rows(store, range(256))
        p99 = float("inf")
        for _ in range(2):
            with tlock:
                times.clear()
            time.sleep(1.5)
            with tlock:
                gaps = [np.diff(v) for v in times.values() if len(v) > 2]
            p99 = min(p99, float(np.percentile(np.concatenate(gaps), 99)))
            if p99 <= 3 * interval:
                break
        assert p99 <= 3 * interval, (
            f"p99 row staleness {p99 * 1e3:.0f}ms exceeds "
            f"{3 * interval * 1e3:.0f}ms")
        assert threading.active_count() - before <= eng.workers + 2
    finally:
        eng.close()


def test_tier1_guard_no_per_endpoint_threads():
    """Tier-1 regression guard: endpoint attachment through EVERY
    production-facing scraper surface (ScrapeEngine and the legacy-API
    Scraper adapter the runner historically used) must not spawn
    per-endpoint daemon threads again. 64 attaches may add at most the
    worker-shard pool."""
    for make in (
        lambda st: ScrapeEngine(st, interval_s=0.05,
                                fetcher=lambda url: VLLM_TEXT),
        lambda st: Scraper(st, interval_s=0.05,
                           fetcher=lambda url: VLLM_TEXT),
    ):
        before = threading.active_count()
        sc = make(MetricsStore())
        for slot in range(64):
            sc.attach(slot, f"http://10.3.0.{slot}:8000/m", VLLM)
        delta = threading.active_count() - before
        sc.close()
        assert delta <= 8 + 2, (
            f"{delta} threads spawned for 64 endpoints — per-endpoint "
            "polling threads are back")


def test_detach_while_fetch_hung_returns_quickly():
    """detach() must return well under 100 ms even while the detached
    endpoint's fetch is wedged, and the slot's row must stay cleared
    (the late fetch result is discarded, never resurrected)."""
    hang = threading.Event()
    started = threading.Event()

    def fetcher(url):
        if "slow" in url:
            started.set()
            hang.wait(5)
            return VLLM_TEXT
        return VLLM_TEXT

    store = MetricsStore()
    eng = ScrapeEngine(store, interval_s=0.01, fetcher=fetcher, workers=1)
    try:
        eng.attach(0, "http://10.4.0.1:8000/slow", VLLM)
        assert started.wait(2), "hung fetch never started"
        t0 = time.monotonic()
        eng.detach(0)
        took = time.monotonic() - t0
        assert took < 0.1, f"detach blocked {took * 1e3:.0f}ms on hung fetch"
        assert not store._has_data[0]
        hang.set()
        time.sleep(0.1)  # let the late result flow through the shard
        assert not store._has_data[0], "late fetch resurrected a detached row"
    finally:
        hang.set()
        eng.close()


def test_backoff_doubles_and_snaps_back():
    """Unreachable endpoints back off (effective interval doubling, so
    dead pods stop taxing the shard) and snap back to the base cadence on
    the first success."""
    mode = {"fail": True}
    calls: list[float] = []

    def fetcher(url):
        calls.append(time.monotonic())
        if mode["fail"]:
            raise ConnectionError("down")
        return VLLM_TEXT

    store = MetricsStore()
    eng = ScrapeEngine(store, interval_s=0.01, fetcher=fetcher, workers=1,
                       max_backoff_s=0.2, jitter=0.0)
    try:
        eng.attach(0, "http://10.5.0.1:8000/m", VLLM)
        deadline = time.monotonic() + 3
        while time.monotonic() < deadline:
            if eng.consecutive_failures_max() >= 4:
                break
            time.sleep(0.01)
        assert eng.consecutive_failures_max() >= 4
        with eng._lock:
            ep = eng._live[0]
        gaps = np.diff(calls[: len(calls)])
        # The failure gaps grow toward the cap: the last observed gap must
        # dwarf the base interval.
        assert gaps[-1] > 0.03, f"no backoff growth: gaps {gaps}"
        # Recovery: one success snaps the cadence back and fills the row.
        mode["fail"] = False
        _wait_rows(store, [0])
        deadline = time.monotonic() + 2
        while time.monotonic() < deadline and eng.consecutive_failures_max():
            time.sleep(0.005)
        assert eng.consecutive_failures_max() == 0
        n0 = len(calls)
        time.sleep(0.2)
        # Back at ~10 ms cadence: >= 8 scrapes in 200 ms (vs ~1 at the cap).
        assert len(calls) - n0 >= 8, "cadence did not snap back after success"
    finally:
        eng.close()


def test_update_rows_matches_update():
    """The batched write path must be observationally identical to the
    per-row path (same rows, ages, wake/flag semantics)."""
    a, b = MetricsStore(), MetricsStore()
    rows = [
        (3, {0: 1.0, 2: 0.5}, [1, 2], [3]),
        (7, {1: 9.0}, [], [4, 5]),
    ]
    now = time.time()
    for slot, metrics, act, wait in rows:
        a.update(slot, metrics, act, wait, now=now)
    b.update_rows(rows, now=now)
    assert a._metrics.tobytes() == b._metrics.tobytes()
    assert a._lora_active.tobytes() == b._lora_active.tobytes()
    assert a._lora_waiting.tobytes() == b._lora_waiting.tobytes()
    assert (a._scraped_at == b._scraped_at).all()
    assert (a._has_data == b._has_data).all()


def test_keepalive_http_path_reuses_connections():
    """The engine's real fetch path: persistent http.client connections
    against an HTTP/1.1 server — rows land and connections are reused
    across scrapes (the whole point of replacing per-scrape urllib)."""
    body = VLLM_TEXT.encode()

    class H(http.server.BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def do_GET(self):
            self.send_response(200)
            self.send_header("Content-Type", "text/plain")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), H)
    httpd.daemon_threads = True
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    port = httpd.server_address[1]
    store = MetricsStore()
    eng = ScrapeEngine(store, interval_s=0.02, workers=2)
    try:
        for slot in range(3):
            eng.attach(slot, f"http://127.0.0.1:{port}/metrics", VLLM)
        _wait_rows(store, range(3))
        time.sleep(0.3)  # several scrapes past the first
        assert eng.connection_reuse_ratio() > 0.5, (
            f"keep-alive not reusing: ratio {eng.connection_reuse_ratio()}")
        from gie_tpu.sched.constants import Metric

        assert store._metrics[0, Metric.QUEUE_DEPTH] == 7
    finally:
        eng.close()
        httpd.shutdown()
        httpd.server_close()


def test_staleness_seconds_tracks_outage():
    """staleness_seconds() — the autoscale SignalCollector's second
    staleness input — grows during a fetch outage and resets on
    recovery."""
    mode = {"fail": False}

    def fetcher(url):
        if mode["fail"]:
            raise ConnectionError("down")
        return VLLM_TEXT

    store = MetricsStore()
    eng = ScrapeEngine(store, interval_s=0.01, fetcher=fetcher, workers=1)
    try:
        eng.attach(0, "http://10.6.0.1:8000/m", VLLM)
        _wait_rows(store, [0])
        assert eng.staleness_seconds() < 1.0
        mode["fail"] = True
        time.sleep(0.3)
        assert eng.staleness_seconds() >= 0.2
        mode["fail"] = False
        deadline = time.monotonic() + 2
        while time.monotonic() < deadline:
            if eng.staleness_seconds() < 0.1:
                break
            time.sleep(0.01)
        assert eng.staleness_seconds() < 0.1
    finally:
        eng.close()


def test_rebind_url_repoints_same_slot():
    """Re-attaching a slot at a new URL (pod IP change) must poll the new
    address and stop polling the old one, without a restart join."""
    polled = set()

    def fetcher(url):
        polled.add(url)
        return VLLM_TEXT

    store = MetricsStore()
    eng = ScrapeEngine(store, interval_s=0.01, fetcher=fetcher, workers=1)
    try:
        eng.attach(0, "http://old:8000/m", VLLM)
        _wait_rows(store, [0])
        eng.attach(0, "http://new:8000/m", VLLM)
        deadline = time.monotonic() + 2
        while time.monotonic() < deadline and "http://new:8000/m" not in polled:
            time.sleep(0.005)
        assert "http://new:8000/m" in polled
        polled.clear()
        time.sleep(0.1)
        assert "http://old:8000/m" not in polled, "old URL still polled"
        assert eng.endpoint_count() == 1
    finally:
        eng.close()
