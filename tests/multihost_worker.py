"""Worker process for the multi-host test (not collected by pytest).

Usage: python tests/multihost_worker.py <process_id> <num_processes> <port>
Joins the distributed system, runs one dp-sharded predictor train step on
the global mesh with a process-local batch shard, prints the loss.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

from gie_tpu.models.latency import NUM_FEATURES  # noqa: E402
from gie_tpu.parallel import multihost  # noqa: E402


def main() -> None:
    pid, nproc, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
    multihost.initialize(f"127.0.0.1:{port}", nproc, pid)
    mesh = multihost.global_mesh(tp=1)
    step, params, opt_state = multihost.multihost_train_step(mesh)

    # Each process supplies only ITS shard of the global batch.
    per_host = 8
    rng = np.random.default_rng(pid)
    feats = rng.uniform(0, 1, (per_host, NUM_FEATURES)).astype(np.float32)
    slots = rng.integers(0, 8, (per_host,)).astype(np.int32)
    targets = rng.uniform(0, 1, (per_host, 2)).astype(np.float32)
    weights = np.ones((per_host, 2), np.float32)

    g_feats = multihost.host_local_batch_to_global(mesh, feats)
    g_slots = multihost.host_local_batch_to_global(mesh, slots)
    g_targets = multihost.host_local_batch_to_global(mesh, targets)
    g_weights = multihost.host_local_batch_to_global(mesh, weights)

    params, opt_state, loss = step(params, opt_state, g_feats, g_slots,
                                   g_targets, g_weights)
    jax.block_until_ready(loss)
    print(f"MULTIHOST_OK pid={pid} devices={len(jax.devices())} "
          f"loss={float(loss):.6f}", flush=True)


if __name__ == "__main__":
    main()
