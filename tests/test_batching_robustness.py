"""Collector-thread robustness + assumed-load release accounting.

ADVICE r1 regressions: (1) a failure in the collector's pre-batch section
(fair ordering / band resolution) must fail the waiting picks, not kill the
collector and hang every future request; (2) pick() must not wait forever on
a wedged collector; (3) served feedback must release the slot that was
CHARGED (the primary pick), not the slot of the endpoint that happened to
serve after data-plane failover.

ISSUE 1 (pipelined collector) adds: (4) a device error materializing wave k
fails only wave k's waiters while the pipeline keeps serving wave k+1;
(5) close() drains dispatched waves instead of abandoning them; (6) the
two-stage collector genuinely OVERLAPS host assembly/dispatch with the
device cycle — W waves finish measurably faster than W x (assembly+cycle).

Note on (1): since ISSUE 1 the criticality band is resolved ONCE at enqueue
(cached on _Pending), so a malformed objective header now fails its own
pick() with INVALID_ARGUMENT at the call site — it can no longer reach the
collector's pre-batch section at all. The test keeps asserting the contract
that matters: the poisoned picks fail with ExtProcError and the collector
keeps serving.
"""

import threading
import time
from types import SimpleNamespace

import grpc
import pytest

from gie_tpu.datastore import Datastore
from gie_tpu.datastore.objects import EndpointPool, Pod
from gie_tpu.extproc.server import ExtProcError, PickRequest
from gie_tpu.extproc import metadata as mdkeys
from gie_tpu.metricsio import MetricsStore
from gie_tpu.sched import ProfileConfig, Scheduler
from gie_tpu.sched.batching import BatchingTPUPicker


def _stack(n_pods=2, **picker_kw):
    sched = Scheduler(ProfileConfig(load_decay=1.0))
    ms = MetricsStore()
    ds = Datastore(on_slot_reclaimed=lambda s: (sched.evict_endpoint(s),
                                                ms.remove(s)))
    ds.pool_set(EndpointPool({"app": "x"}, [8000], "default"))
    for i in range(n_pods):
        ds.pod_update_or_add(
            Pod(name=f"p{i}", labels={"app": "x"}, ip=f"10.9.0.{i + 1}")
        )
    picker = BatchingTPUPicker(sched, ds, ms, max_wait_s=0.02, **picker_kw)
    return sched, ds, ms, picker


def test_collector_survives_poisoned_prebatch_section():
    """A request whose headers break _fair_order (value is None, not a list)
    must fail with INTERNAL — and the collector must keep serving."""
    sched, ds, ms, picker = _stack(max_batch=1)
    try:
        poison = PickRequest(headers={mdkeys.OBJECTIVE_KEY: None}, body=b"x")
        results = []

        def one_pick():
            try:
                results.append(picker.pick(poison, ds.endpoints()))
            except ExtProcError as e:
                results.append(e)

        # Two concurrent poisoned picks force len(pending) > max_batch, which
        # routes through _fair_order -> _band_for -> None[0] TypeError in the
        # pre-batch section (outside _run_batch's own error handling).
        threads = [threading.Thread(target=one_pick) for _ in range(2)]
        [t.start() for t in threads]
        [t.join(timeout=10) for t in threads]
        assert len(results) == 2
        assert all(isinstance(r, ExtProcError) for r in results)
        # The collector is still alive: a well-formed pick succeeds.
        ok = picker.pick(PickRequest(headers={}, body=b"good"), ds.endpoints())
        assert ":" in ok.endpoint
    finally:
        picker.close()


def test_pick_times_out_instead_of_hanging():
    sched, ds, ms, picker = _stack(pick_timeout_s=0.3)
    try:
        picker._run_batch = lambda batch: time.sleep(2.0) or []
        with pytest.raises(ExtProcError) as exc:
            picker.pick(PickRequest(headers={}, body=b"x"), ds.endpoints())
        assert exc.value.code == grpc.StatusCode.UNAVAILABLE
    finally:
        picker.close()


def test_failover_releases_charged_primary_slot():
    sched, ds, ms, picker = _stack()
    try:
        res = picker.pick(PickRequest(headers={}, body=b"hello"),
                          ds.endpoints())
        primary_slot = ds.endpoint_by_hostport(res.endpoint).slot
        assert res.charged_slot == primary_slot
        load = sched.snapshot_assumed_load()
        assert load[primary_slot] > 0.0
        # The data plane fails over: the FALLBACK serves, but the release
        # must still land on the charged primary slot.
        served = res.fallbacks[0] if res.fallbacks else res.endpoint
        fallback_slot = ds.endpoint_by_hostport(served).slot
        picker.observe_served(served, SimpleNamespace(pick_result=res))
        after = sched.snapshot_assumed_load()
        assert after[primary_slot] == pytest.approx(0.0, abs=1e-6)
        if fallback_slot != primary_slot:
            assert after[fallback_slot] == pytest.approx(
                float(load[fallback_slot]), abs=1e-6)
    finally:
        picker.close()


def test_release_skipped_when_primary_was_evicted():
    """If the charged endpoint is gone (its eviction already cleared the
    slot), the release must not subtract from a reused slot."""
    sched, ds, ms, picker = _stack()
    try:
        res = picker.pick(PickRequest(headers={}, body=b"hello"),
                          ds.endpoints())
        primary = ds.endpoint_by_hostport(res.endpoint)
        ds.pod_delete("default", primary.pod_name)  # evicts + clears load
        # A new pod reuses the freed slot.
        ds.pod_update_or_add(
            Pod(name="fresh", labels={"app": "x"}, ip="10.9.0.99")
        )
        reused = {e.slot for e in ds.endpoints()}
        assert primary.slot in reused
        before = sched.snapshot_assumed_load().copy()
        picker.observe_served(res.endpoint, SimpleNamespace(pick_result=res))
        after = sched.snapshot_assumed_load()
        assert list(after) == list(before)  # no spurious release anywhere
    finally:
        picker.close()


def test_device_error_isolated_to_single_wave():
    """Pipeline fault isolation (ISSUE 1): a device failure materializing
    wave k fails only wave k's waiters with INTERNAL; the completer keeps
    serving wave k+1."""
    sched, ds, ms, picker = _stack(max_batch=1)
    try:
        real = sched.pick_async
        calls = {"n": 0}

        class _Poisoned:
            def materialize(self):
                raise RuntimeError("device poisoned")

            def materialize_load(self):
                return None

        def flaky(reqs, eps, **kw):
            pw = real(reqs, eps, **kw)
            calls["n"] += 1
            return _Poisoned() if calls["n"] == 1 else pw

        sched.pick_async = flaky
        with pytest.raises(ExtProcError) as exc:
            picker.pick(PickRequest(headers={}, body=b"wave-k"),
                        ds.endpoints())
        assert exc.value.code == grpc.StatusCode.INTERNAL
        # Wave k+1 sails through the same dispatcher AND completer.
        ok = picker.pick(PickRequest(headers={}, body=b"wave-k+1"),
                         ds.endpoints())
        assert ":" in ok.endpoint
    finally:
        picker.close()


def test_close_drains_inflight_waves():
    """close() must complete waves already dispatched to the device — the
    completer drains FIFO up to the close sentinel, so in-flight picks get
    their results instead of hanging until the pick() timeout."""
    sched, ds, ms, picker = _stack(max_batch=1)
    real = sched.pick_async

    class _Slow:
        def __init__(self, inner):
            self.inner = inner

        def materialize(self):
            time.sleep(0.25)
            return self.inner.materialize()

        def materialize_load(self):
            return self.inner.materialize_load()

    sched.pick_async = lambda reqs, eps, **kw: _Slow(real(reqs, eps, **kw))
    results = []

    def one():
        try:
            results.append(
                picker.pick(PickRequest(headers={}, body=b"x"),
                            ds.endpoints()))
        except Exception as e:  # pragma: no cover - the failure mode
            results.append(e)

    threads = [threading.Thread(target=one) for _ in range(3)]
    [t.start() for t in threads]
    time.sleep(0.4)  # let the dispatcher push the waves in flight
    picker.close()
    [t.join(timeout=10) for t in threads]
    assert len(results) == 3
    assert all(hasattr(r, "endpoint") for r in results), results


def test_pipeline_overlaps_assembly_with_device_cycle():
    """The acceptance bar of ISSUE 1: with a stubbed slow cycle, W waves
    through the two-stage collector finish measurably below the serial
    W x (dispatch + materialize) wall time, while every wave's results
    match the synchronous path (here: the stub's known pick)."""
    import numpy as np

    from gie_tpu.sched import constants as C
    from gie_tpu.sched.types import PickResult as SchedPickResult

    sched, ds, ms, picker = _stack(max_batch=1)
    A, T, W = 0.06, 0.06, 4  # stage-1 dispatch cost, device wait, waves
    try:
        class _FakeWave:
            def __init__(self, n):
                self.n = n

            def materialize(self):
                time.sleep(T)  # the device cycle the pipeline hides
                idx = np.full((self.n, C.FALLBACKS), -1, np.int32)
                idx[:, 0] = 0
                return SchedPickResult(
                    indices=idx,
                    status=np.zeros((self.n,), np.int32),
                    scores=np.zeros((self.n, C.FALLBACKS), np.float32),
                )

            def materialize_load(self):
                return None

        def fake_pick_async(reqs, eps, **kw):
            time.sleep(A)  # host-side assembly/dispatch cost
            import numpy as _np
            return _FakeWave(int(_np.asarray(reqs.valid).shape[0]))

        sched.pick_async = fake_pick_async
        slot0 = next(ep.hostport for ep in ds.endpoints() if ep.slot == 0)
        results = []

        def one():
            results.append(
                picker.pick(PickRequest(headers={}, body=b"x"),
                            ds.endpoints()))

        threads = [threading.Thread(target=one) for _ in range(W)]
        t0 = time.perf_counter()
        [t.start() for t in threads]
        [t.join(timeout=10) for t in threads]
        wall = time.perf_counter() - t0
        serial = W * (A + T)
        # Pipelined steady state ~ A + W*T (stage 1 of wave k+1 overlaps
        # stage 2 of wave k); require a clear margin below serial.
        assert wall < serial - 1.5 * T, (
            f"no overlap: {W} waves took {wall:.3f}s, serial is {serial:.3f}s")
        # Per-wave results identical to what the synchronous path would
        # produce from the same (stubbed) cycle output.
        assert len(results) == W
        assert all(getattr(r, "endpoint", None) == slot0 for r in results), results
    finally:
        picker.close()


def test_slo_admission_sheds_and_releases_charge():
    """EPP-side predictive SLO admission: a non-critical request carrying
    x-gateway-inference-ttft-slo-ms whose predicted TTFT misses the bound
    is shed with 429, and the charge the cycle added is released; critical
    requests are never shed."""
    import numpy as np
    from gie_tpu.models.latency import LatencyPredictor, OnlineTrainer

    sched = Scheduler(ProfileConfig(load_decay=1.0))
    ms = MetricsStore()
    ds = Datastore()
    ds.pool_set(EndpointPool({"app": "x"}, [8000], "default"))
    for i in range(2):
        ds.pod_update_or_add(
            Pod(name=f"p{i}", labels={"app": "x"}, ip=f"10.9.1.{i + 1}"))
    trainer = OnlineTrainer(LatencyPredictor())
    trainer.predict_ttft = lambda feats, slots: np.full(
        (len(slots),), 9.9, np.float32)  # everything predicted hopeless
    picker = BatchingTPUPicker(sched, ds, ms, max_wait_s=0.02,
                               trainer=trainer)
    try:
        slo_headers = {mdkeys.TTFT_SLO_MS_KEY: ["500"]}
        # Cold start (no train step yet): admission must NOT engage —
        # random-init predictions would 429 valid traffic.
        cold = picker.pick(PickRequest(headers=slo_headers, body=b"x"),
                           ds.endpoints())
        assert ":" in cold.endpoint
        picker.observe_served(
            cold.endpoint, SimpleNamespace(pick_result=cold))
        trainer.last_loss = 0.01  # model has fit something
        with pytest.raises(Exception) as exc:
            picker.pick(PickRequest(headers=slo_headers, body=b"x"),
                        ds.endpoints())
        assert type(exc.value).__name__ == "ShedError"
        # The shed request's charge was released.
        assert float(sched.snapshot_assumed_load().sum()) == pytest.approx(
            0.0, abs=1e-6)
        # No SLO header -> served normally despite hopeless predictions.
        ok = picker.pick(PickRequest(headers={}, body=b"x"), ds.endpoints())
        assert ":" in ok.endpoint
        # CRITICAL requests bypass admission.
        crit = picker.pick(
            PickRequest(headers={**slo_headers,
                                 mdkeys.OBJECTIVE_KEY: ["critical"]},
                        body=b"x"),
            ds.endpoints())
        assert ":" in crit.endpoint
    finally:
        picker.close()


def test_dispatcher_kicks_background_lattice_warm_once():
    """With background_warm=True (the runner's production wiring), the
    dispatcher's first wave at a new (M, chunk) lattice hands the REST of
    that lattice's N buckets to Scheduler.warm_lattice_async — once per
    lattice — so later load spikes never stall on first-use jit. Opt-in:
    a picker built without the flag must kick nothing (deterministic
    latency tests rely on that)."""
    from gie_tpu.sched import constants as C
    from gie_tpu.extproc.server import PickRequest

    sched, ds, ms, picker = _stack(background_warm=True)
    try:
        picker.pick(PickRequest(headers={}, body=b"x"), ds.endpoints())
        assert len(picker._warm_threads) == 1
        picker._warm_threads[0].join(timeout=600)
        assert not picker._warm_threads[0].is_alive()
        # The whole N lattice for (M_BUCKETS[0], C_BUCKETS[0]) is warm.
        lanes = C.C_BUCKETS[0]
        for n in C.N_BUCKETS:
            assert (n, C.M_BUCKETS[0], lanes) in sched._warm_buckets
        # Same lattice again: no second kick.
        picker.pick(PickRequest(headers={}, body=b"y"), ds.endpoints())
        assert len(picker._warm_threads) == 1
    finally:
        picker.close()

    sched2, ds2, ms2, picker2 = _stack()  # default: off
    try:
        picker2.pick(PickRequest(headers={}, body=b"x"), ds2.endpoints())
        assert picker2._warm_threads == []
    finally:
        picker2.close()


# ---------------------------------------------------------------------------
# adaptive pipeline depth (ROADMAP PR 1 follow-up)


def test_pipeline_depth_auto_policy_and_hysteresis():
    """pipeline_depth="auto" derives the in-flight bound 1-3 from the
    measured host-assembly / device-cycle ratio, with two-agreeing-
    retunes hysteresis so a ratio sitting on a threshold cannot flap the
    bound every window."""
    sched, ds, ms, picker = _stack(pipeline_depth="auto")
    try:
        assert picker._depth_auto and picker._depth_limit == 2

        def retune(asm, cycle, times=2):
            picker._asm_ewma, picker._cycle_ewma = asm, cycle
            for _ in range(times):
                picker._retune_depth()

        retune(3.0e-3, 1.0e-3)           # host-bound: bound never binds
        assert picker._depth_limit == 1
        retune(1.0e-3, 1.0e-3)           # balanced: absorb assembly jitter
        assert picker._depth_limit == 3
        retune(0.1e-3, 1.0e-3)           # device-bound: double buffer
        assert picker._depth_limit == 2
        # Hysteresis: ONE deviating window must not move the bound.
        retune(3.0e-3, 1.0e-3, times=1)
        assert picker._depth_limit == 2
        retune(3.0e-3, 1.0e-3, times=1)  # second agreement applies it
        assert picker._depth_limit == 1
        # No measurements yet -> no change (fresh picker guard).
        picker._asm_ewma = picker._cycle_ewma = 0.0
        picker._retune_depth()
        assert picker._depth_limit == 1
    finally:
        picker.close()


def test_pipeline_depth_auto_serves_picks():
    """End to end: an auto-depth picker keeps the dispatcher/completer
    pipeline correct (picks fan out, in-flight accounting drains to
    zero on close)."""
    sched, ds, ms, picker = _stack(pipeline_depth="auto")
    try:
        for i in range(6):
            res = picker.pick(
                PickRequest(headers={}, body=b"hello %d" % i),
                ds.endpoints())
            assert res.endpoint
        # EWMAs captured real stage times for the auto policy.
        assert picker._asm_ewma > 0.0 and picker._cycle_ewma > 0.0
    finally:
        picker.close()
    assert picker._inflight == 0


def test_pipeline_depth_validation():
    import pytest as _pytest

    for bad in (0, -1, "bogus", 1.5):
        with _pytest.raises(ValueError):
            _stack(pipeline_depth=bad)
