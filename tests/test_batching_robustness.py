"""Collector-thread robustness + assumed-load release accounting.

ADVICE r1 regressions: (1) a failure in the collector's pre-batch section
(fair ordering / band resolution) must fail the waiting picks, not kill the
collector and hang every future request; (2) pick() must not wait forever on
a wedged collector; (3) served feedback must release the slot that was
CHARGED (the primary pick), not the slot of the endpoint that happened to
serve after data-plane failover.
"""

import threading
import time
from types import SimpleNamespace

import grpc
import pytest

from gie_tpu.datastore import Datastore
from gie_tpu.datastore.objects import EndpointPool, Pod
from gie_tpu.extproc.server import ExtProcError, PickRequest
from gie_tpu.extproc import metadata as mdkeys
from gie_tpu.metricsio import MetricsStore
from gie_tpu.sched import ProfileConfig, Scheduler
from gie_tpu.sched.batching import BatchingTPUPicker


def _stack(n_pods=2, **picker_kw):
    sched = Scheduler(ProfileConfig(load_decay=1.0))
    ms = MetricsStore()
    ds = Datastore(on_slot_reclaimed=lambda s: (sched.evict_endpoint(s),
                                                ms.remove(s)))
    ds.pool_set(EndpointPool({"app": "x"}, [8000], "default"))
    for i in range(n_pods):
        ds.pod_update_or_add(
            Pod(name=f"p{i}", labels={"app": "x"}, ip=f"10.9.0.{i + 1}")
        )
    picker = BatchingTPUPicker(sched, ds, ms, max_wait_s=0.02, **picker_kw)
    return sched, ds, ms, picker


def test_collector_survives_poisoned_prebatch_section():
    """A request whose headers break _fair_order (value is None, not a list)
    must fail with INTERNAL — and the collector must keep serving."""
    sched, ds, ms, picker = _stack(max_batch=1)
    try:
        poison = PickRequest(headers={mdkeys.OBJECTIVE_KEY: None}, body=b"x")
        results = []

        def one_pick():
            try:
                results.append(picker.pick(poison, ds.endpoints()))
            except ExtProcError as e:
                results.append(e)

        # Two concurrent poisoned picks force len(pending) > max_batch, which
        # routes through _fair_order -> _band_for -> None[0] TypeError in the
        # pre-batch section (outside _run_batch's own error handling).
        threads = [threading.Thread(target=one_pick) for _ in range(2)]
        [t.start() for t in threads]
        [t.join(timeout=10) for t in threads]
        assert len(results) == 2
        assert all(isinstance(r, ExtProcError) for r in results)
        # The collector is still alive: a well-formed pick succeeds.
        ok = picker.pick(PickRequest(headers={}, body=b"good"), ds.endpoints())
        assert ":" in ok.endpoint
    finally:
        picker.close()


def test_pick_times_out_instead_of_hanging():
    sched, ds, ms, picker = _stack(pick_timeout_s=0.3)
    try:
        picker._run_batch = lambda batch: time.sleep(2.0) or []
        with pytest.raises(ExtProcError) as exc:
            picker.pick(PickRequest(headers={}, body=b"x"), ds.endpoints())
        assert exc.value.code == grpc.StatusCode.UNAVAILABLE
    finally:
        picker.close()


def test_failover_releases_charged_primary_slot():
    sched, ds, ms, picker = _stack()
    try:
        res = picker.pick(PickRequest(headers={}, body=b"hello"),
                          ds.endpoints())
        primary_slot = ds.endpoint_by_hostport(res.endpoint).slot
        assert res.charged_slot == primary_slot
        load = sched.snapshot_assumed_load()
        assert load[primary_slot] > 0.0
        # The data plane fails over: the FALLBACK serves, but the release
        # must still land on the charged primary slot.
        served = res.fallbacks[0] if res.fallbacks else res.endpoint
        fallback_slot = ds.endpoint_by_hostport(served).slot
        picker.observe_served(served, SimpleNamespace(pick_result=res))
        after = sched.snapshot_assumed_load()
        assert after[primary_slot] == pytest.approx(0.0, abs=1e-6)
        if fallback_slot != primary_slot:
            assert after[fallback_slot] == pytest.approx(
                float(load[fallback_slot]), abs=1e-6)
    finally:
        picker.close()


def test_release_skipped_when_primary_was_evicted():
    """If the charged endpoint is gone (its eviction already cleared the
    slot), the release must not subtract from a reused slot."""
    sched, ds, ms, picker = _stack()
    try:
        res = picker.pick(PickRequest(headers={}, body=b"hello"),
                          ds.endpoints())
        primary = ds.endpoint_by_hostport(res.endpoint)
        ds.pod_delete("default", primary.pod_name)  # evicts + clears load
        # A new pod reuses the freed slot.
        ds.pod_update_or_add(
            Pod(name="fresh", labels={"app": "x"}, ip="10.9.0.99")
        )
        reused = {e.slot for e in ds.endpoints()}
        assert primary.slot in reused
        before = sched.snapshot_assumed_load().copy()
        picker.observe_served(res.endpoint, SimpleNamespace(pick_result=res))
        after = sched.snapshot_assumed_load()
        assert list(after) == list(before)  # no spurious release anywhere
    finally:
        picker.close()


def test_slo_admission_sheds_and_releases_charge():
    """EPP-side predictive SLO admission: a non-critical request carrying
    x-gateway-inference-ttft-slo-ms whose predicted TTFT misses the bound
    is shed with 429, and the charge the cycle added is released; critical
    requests are never shed."""
    import numpy as np
    from gie_tpu.models.latency import LatencyPredictor, OnlineTrainer

    sched = Scheduler(ProfileConfig(load_decay=1.0))
    ms = MetricsStore()
    ds = Datastore()
    ds.pool_set(EndpointPool({"app": "x"}, [8000], "default"))
    for i in range(2):
        ds.pod_update_or_add(
            Pod(name=f"p{i}", labels={"app": "x"}, ip=f"10.9.1.{i + 1}"))
    trainer = OnlineTrainer(LatencyPredictor())
    trainer.predict_ttft = lambda feats, slots: np.full(
        (len(slots),), 9.9, np.float32)  # everything predicted hopeless
    picker = BatchingTPUPicker(sched, ds, ms, max_wait_s=0.02,
                               trainer=trainer)
    try:
        slo_headers = {mdkeys.TTFT_SLO_MS_KEY: ["500"]}
        # Cold start (no train step yet): admission must NOT engage —
        # random-init predictions would 429 valid traffic.
        cold = picker.pick(PickRequest(headers=slo_headers, body=b"x"),
                           ds.endpoints())
        assert ":" in cold.endpoint
        picker.observe_served(
            cold.endpoint, SimpleNamespace(pick_result=cold))
        trainer.last_loss = 0.01  # model has fit something
        with pytest.raises(Exception) as exc:
            picker.pick(PickRequest(headers=slo_headers, body=b"x"),
                        ds.endpoints())
        assert type(exc.value).__name__ == "ShedError"
        # The shed request's charge was released.
        assert float(sched.snapshot_assumed_load().sum()) == pytest.approx(
            0.0, abs=1e-6)
        # No SLO header -> served normally despite hopeless predictions.
        ok = picker.pick(PickRequest(headers={}, body=b"x"), ds.endpoints())
        assert ":" in ok.endpoint
        # CRITICAL requests bypass admission.
        crit = picker.pick(
            PickRequest(headers={**slo_headers,
                                 mdkeys.OBJECTIVE_KEY: ["critical"]},
                        body=b"x"),
            ds.endpoints())
        assert ":" in crit.endpoint
    finally:
        picker.close()
