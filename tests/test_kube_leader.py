"""Distributed (Lease-object) leader election against the fake apiserver.

VERDICT r3 #6: the file elector is single-node; real multi-replica EPP
deployments elect on a coordination.k8s.io Lease (reference
internal/runnable/leader_election.go; readiness semantics 004
README:111-115). These tests contend two electors through the REAL
stdlib kube adapter against tests/fakeapi's Lease endpoints (optimistic
concurrency included) and pin: single leader, failover on expiry (crash)
and on graceful release, follower readiness, and the runner wiring.
"""

import time

import pytest

from gie_tpu.controller.kube import KubeClusterClient
from gie_tpu.runtime.leader import KubeLeaseElector
from tests.fakeapi import FakeKubeApiServer

NS = "default"


def _wait(predicate, timeout_s: float = 5.0) -> bool:
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(0.02)
    return False


@pytest.fixture()
def apiserver():
    srv = FakeKubeApiServer()
    yield srv
    srv.close()


def _elector(srv, ident, ttl=0.6, renew=0.08) -> KubeLeaseElector:
    client = KubeClusterClient(NS, "pool", server=srv.url, token="t")
    return KubeLeaseElector(
        client, NS, "pool-epp-leader", identity=ident,
        lease_ttl_s=ttl, renew_interval_s=renew)


def _leaders(*electors) -> list[bool]:
    return [e.is_leader() for e in electors]


def test_exactly_one_leader_under_contention(apiserver):
    a, b = _elector(apiserver, "a"), _elector(apiserver, "b")
    a.start(), b.start()
    try:
        assert _wait(lambda: sum(_leaders(a, b)) == 1), "no leader elected"
        # Stable over several renew cycles: never two leaders.
        for _ in range(10):
            time.sleep(0.08)
            assert sum(_leaders(a, b)) <= 1, "split brain"
        assert sum(_leaders(a, b)) == 1
    finally:
        a.stop(), b.stop()


def test_failover_on_lease_expiry_after_crash(apiserver):
    a, b = _elector(apiserver, "a"), _elector(apiserver, "b")
    a.start()
    assert _wait(lambda: a.is_leader())
    b.start()
    try:
        time.sleep(0.2)
        assert not b.is_leader(), "follower grabbed a live lease"
        # Crash the leader: stop its renew loop WITHOUT the graceful
        # release (stop() would blank the holder; a crash cannot).
        a._stop.set()
        a._thread.join(timeout=2)
        assert _wait(lambda: b.is_leader(), timeout_s=3.0), (
            "no takeover after the lease expired")
    finally:
        b.stop()


def test_graceful_release_fails_over_fast(apiserver):
    a = _elector(apiserver, "a", ttl=30.0)  # TTL too long to expire here
    b = _elector(apiserver, "b", ttl=30.0)
    a.start()
    assert _wait(lambda: a.is_leader())
    b.start()
    try:
        time.sleep(0.2)
        a.stop()  # graceful: blanks holderIdentity
        assert _wait(lambda: b.is_leader(), timeout_s=3.0), (
            "released lease not claimed without waiting out the TTL")
    finally:
        b.stop()


def test_unreachable_apiserver_grace_then_follower(apiserver):
    """A transient apiserver outage must NOT blip readiness instantly:
    the last written lease still blocks every other replica, so
    leadership holds through the grace window — and then fails safe to
    follower once the lease would have expired."""
    a = _elector(apiserver, "a", ttl=0.8, renew=0.08)
    a.start()
    assert _wait(lambda: a.is_leader())
    apiserver.close()
    time.sleep(0.3)  # several failed renews, still inside the window
    assert a.is_leader(), "one blip dropped leadership (no grace)"
    assert _wait(lambda: not a.is_leader(), timeout_s=3.0), (
        "leadership outlived the lease it could no longer renew")
    a._stop.set()
    a._thread.join(timeout=2)


def test_skewed_record_timestamps_cannot_steal_a_live_lease(apiserver):
    """Expiry is judged by local observation of record CHANGES, never by
    comparing the record's wall-clock renewTime to ours: a live leader
    whose clock is decades behind keeps its lease as long as it renews."""
    lease_name = "pool-epp-leader"
    seq = {"n": 0}

    def foreign_renew():
        # A "skewed leader": renewTime strings from 1970, but changing —
        # the lease is live by observation.
        seq["n"] += 1
        apiserver.apply("leases", {
            "metadata": {"name": lease_name, "namespace": NS},
            "spec": {
                "holderIdentity": "skewed-leader",
                "leaseDurationSeconds": 1,
                "renewTime": f"1970-01-01T00:00:{seq['n'] % 60:02d}.000000Z",
            },
        })

    foreign_renew()
    b = _elector(apiserver, "b", ttl=0.4, renew=0.05)
    b.start()
    try:
        for _ in range(12):  # keep renewing while b watches
            time.sleep(0.1)
            foreign_renew()
            assert not b.is_leader(), (
                "takeover from a LIVE leader on wall-clock comparison")
        # The skewed leader stops renewing: record sits unchanged ->
        # locally-observed expiry -> legitimate takeover.
        assert _wait(lambda: b.is_leader(), timeout_s=3.0)
    finally:
        b.stop()


def test_runner_wires_kube_elector_and_gates_readiness(apiserver):
    """An ExtProcServerRunner on a kube cluster client + --leader-elect
    must elect over the Lease API and gate ready() on leadership."""
    from gie_tpu.runtime.options import Options
    from gie_tpu.runtime.runner import ExtProcServerRunner

    client = KubeClusterClient(NS, "pool", server=apiserver.url, token="t")
    opts = Options(pool_name="pool", leader_elect=True)
    runner = ExtProcServerRunner(opts, client)
    assert isinstance(runner.elector, KubeLeaseElector)
    runner.elector.lease_ttl_s = 0.6
    runner.elector.renew_interval_s = 0.08
    runner.elector.start()
    try:
        assert _wait(lambda: runner.elector.is_leader())
        # Datastore not synced yet -> not ready even as leader.
        assert runner.ready() is False
        # A second contender stays follower -> its runner would stay
        # NOT_SERVING on readiness (004 README:111-115).
        b = _elector(apiserver, "b")
        b.start()
        try:
            time.sleep(0.25)
            assert not b.is_leader()
        finally:
            b.stop()
    finally:
        # Full stop, not just the elector: the runner's ScrapeEngine
        # shards otherwise outlive the test and keep rewriting global
        # gauges (gie_breaker_open_endpoints) for the rest of the run.
        runner.stop()
