"""The minimum end-to-end slice (SURVEY.md section 7.3): ext-proc stream ->
StreamingServer -> BatchingTPUPicker -> batched Scheduler on the
virtual mesh -> destination header mutation, with live-ish metrics."""

import threading

import grpc
import numpy as np
import pytest

from gie_tpu.datastore import Datastore
from gie_tpu.datastore.objects import EndpointPool
from gie_tpu.extproc import StreamingServer, metadata as mdkeys, pb
from gie_tpu.extproc.server import ExtProcError, ShedError
from gie_tpu.metricsio import MetricsStore
from gie_tpu.sched import Criticality, Metric, ProfileConfig, Scheduler
from gie_tpu.sched.batching import BatchingTPUPicker
from tests.test_datastore import make_pod
from tests.test_extproc import FakeStream, body_msg, dest_header, headers_msg


@pytest.fixture
def stack():
    sched = Scheduler(ProfileConfig())
    ms = MetricsStore()

    def reclaimed(slot):
        sched.evict_endpoint(slot)
        ms.remove(slot)

    ds = Datastore(on_slot_reclaimed=reclaimed)
    ds.pool_set(
        EndpointPool(selector={"app": "vllm"}, target_ports=[8000],
                     namespace="default")
    )
    for i in range(4):
        ds.pod_update_or_add(make_pod(name=f"p{i}", ip=f"10.0.0.{i}"))
    picker = BatchingTPUPicker(sched, ds, ms, max_wait_s=0.005)
    srv = StreamingServer(ds, picker, on_served=picker.observe_served)
    yield srv, ds, ms, sched, picker
    picker.close()


def run_request(srv, prompt=b"", headers=None, metadata_struct=None):
    msgs = [headers_msg(headers=headers, end_of_stream=not prompt,
                        metadata_struct=metadata_struct)]
    if prompt:
        msgs.append(body_msg(prompt, end_of_stream=True))
    stream = FakeStream(msgs)
    srv.process(stream)
    return stream


def test_least_loaded_pick_from_metrics(stack):
    srv, ds, ms, _, _ = stack
    slots = {e.address: e.slot for e in ds.endpoints()}
    ms.update(slots["10.0.0.0"], {Metric.QUEUE_DEPTH: 20, Metric.KV_CACHE_UTIL: 0.9})
    ms.update(slots["10.0.0.1"], {Metric.QUEUE_DEPTH: 0, Metric.KV_CACHE_UTIL: 0.1})
    ms.update(slots["10.0.0.2"], {Metric.QUEUE_DEPTH: 15, Metric.KV_CACHE_UTIL: 0.8})
    ms.update(slots["10.0.0.3"], {Metric.QUEUE_DEPTH: 18, Metric.KV_CACHE_UTIL: 0.85})
    stream = run_request(srv, prompt=b"hello " * 100)
    dest = dest_header(stream.sent[0])
    assert dest.startswith("10.0.0.1:")


def test_concurrent_streams_batched(stack):
    """Many concurrent ext-proc streams must be served by shared scheduling
    cycles and all land on valid endpoints."""
    srv, ds, *_ = stack
    results, errs = [], []

    def one(i):
        try:
            stream = run_request(srv, prompt=b"req %d " % i * 30)
            results.append(dest_header(stream.sent[0]))
        except Exception as e:  # pragma: no cover
            errs.append(e)

    threads = [threading.Thread(target=one, args=(i,)) for i in range(32)]
    [t.start() for t in threads]
    [t.join() for t in threads]
    assert not errs
    valid = {e.hostport for e in ds.endpoints()}
    assert all(r.split(",")[0] in valid for r in results)


def test_prefix_affinity_through_full_stack(stack):
    srv, *_ = stack
    sys_prompt = b"SYSTEM: terse assistant. " * 40
    first = dest_header(run_request(srv, prompt=sys_prompt + b"q1").sent[0])
    again = dest_header(run_request(srv, prompt=sys_prompt + b"q2").sent[0])
    assert first.split(",")[0] == again.split(",")[0]


def test_fallback_list_in_header(stack):
    """Comma-separated ordered fallback list in the destination header
    (004 README:50-82)."""
    srv, ds, *_ = stack
    stream = run_request(srv, prompt=b"x" * 200)
    parts = dest_header(stream.sent[0]).split(",")
    assert len(parts) >= 2
    valid = {e.hostport for e in ds.endpoints()}
    assert all(p in valid for p in parts)
    assert len(set(parts)) == len(parts)


def test_sheddable_429_immediate_response(stack):
    srv, ds, ms, *_ = stack
    for e in ds.endpoints():
        ms.update(e.slot, {Metric.QUEUE_DEPTH: 500, Metric.KV_CACHE_UTIL: 0.99})
    stream = run_request(
        srv,
        prompt=b"shed me",
        headers={mdkeys.OBJECTIVE_KEY: "sheddable"},
    )
    # ImmediateResponse 429 (004 README:80).
    kinds = [r.WhichOneof("response") for r in stream.sent]
    assert kinds == ["immediate_response"]
    assert stream.sent[0].immediate_response.status.code == 429


def test_critical_served_even_saturated(stack):
    srv, ds, ms, *_ = stack
    for e in ds.endpoints():
        ms.update(e.slot, {Metric.QUEUE_DEPTH: 500, Metric.KV_CACHE_UTIL: 0.99})
    stream = run_request(
        srv, prompt=b"vip", headers={mdkeys.OBJECTIVE_KEY: "critical"}
    )
    assert dest_header(stream.sent[0]) is not None


def test_served_feedback_drains_assumed_load(stack):
    srv, ds, ms, sched, _ = stack
    stream = run_request(srv, prompt=b"y" * 4096)
    dest = dest_header(stream.sent[0]).split(",")[0]
    before = sched.snapshot_assumed_load().sum()
    assert before > 0
    served = pb.ProcessingRequest(response_headers=pb.HttpHeaders())
    from google.protobuf import struct_pb2

    st = struct_pb2.Struct()
    st.fields[mdkeys.DESTINATION_ENDPOINT_SERVED_KEY].string_value = dest
    served.metadata_context.filter_metadata[
        mdkeys.DESTINATION_ENDPOINT_NAMESPACE
    ].CopyFrom(st)
    s2 = FakeStream([headers_msg(), served])
    srv.process(s2)
    assert sched.snapshot_assumed_load().sum() < before


def test_pod_churn_mid_traffic(stack):
    """Endpoint slot reuse mid-traffic must not leak stale picks."""
    srv, ds, ms, sched, _ = stack
    run_request(srv, prompt=b"warm")
    ds.pod_delete("default", "p0")
    stream = run_request(srv, prompt=b"after churn")
    dest = dest_header(stream.sent[0])
    assert not dest.split(",")[0].startswith("10.0.0.0:")
    ds.pod_update_or_add(make_pod(name="p9", ip="10.0.0.9"))
    stream = run_request(
        srv, headers={mdkeys.TEST_ENDPOINT_SELECTION_HEADER: "10.0.0.9"}
    )
    assert dest_header(stream.sent[0]) == "10.0.0.9:8000"


def test_sheddable_429_headers_only_request(stack):
    """Bodyless (end_of_stream on headers) sheddable request must also get
    the 429 ImmediateResponse, not a stream error (004 README:80)."""
    srv, ds, ms, *_ = stack
    for e in ds.endpoints():
        ms.update(e.slot, {Metric.QUEUE_DEPTH: 500, Metric.KV_CACHE_UTIL: 0.99})
    stream = run_request(srv, headers={mdkeys.OBJECTIVE_KEY: "sheddable"})
    kinds = [r.WhichOneof("response") for r in stream.sent]
    assert kinds == ["immediate_response"]
    assert stream.sent[0].immediate_response.status.code == 429


def test_flow_control_hold_until_capacity():
    """Flow-control wait queueing: a request picked onto a saturated
    endpoint is held and completes once capacity frees (reference
    flow-control queue-until-capacity semantics)."""
    import time

    sched2 = Scheduler(ProfileConfig())
    ms2 = MetricsStore()
    ds2 = Datastore()
    ds2.pool_set(
        EndpointPool(selector={"app": "vllm"}, target_ports=[8000],
                     namespace="default")
    )
    ds2.pod_update_or_add(make_pod(name="h0", ip="10.0.1.1"))
    slot = ds2.endpoints()[0].slot
    ms2.update(slot, {Metric.QUEUE_DEPTH: 500, Metric.KV_CACHE_UTIL: 0.5})
    picker2 = BatchingTPUPicker(
        sched2, ds2, ms2, max_wait_s=0.002,
        hold_max_s=5.0, hold_queue_limit=100, hold_retry_s=0.01,
    )
    try:
        from gie_tpu.extproc.server import PickRequest

        result_box = {}

        def do_pick():
            result_box["res"] = picker2.pick(
                PickRequest(headers={}, body=b"held request"), ds2.endpoints()
            )

        t = threading.Thread(target=do_pick)
        start = time.monotonic()
        t.start()
        time.sleep(0.3)
        assert t.is_alive()  # held: no capacity yet
        ms2.update(slot, {Metric.QUEUE_DEPTH: 1, Metric.KV_CACHE_UTIL: 0.2})
        t.join(timeout=5)
        assert not t.is_alive()
        assert result_box["res"].endpoint == "10.0.1.1:8000"
        assert time.monotonic() - start < 4.0  # released by capacity, not deadline
    finally:
        picker2.close()


def test_flow_control_deadline_best_effort():
    """Hold deadline expiry resolves best-effort instead of waiting forever."""
    import time

    sched2 = Scheduler(ProfileConfig())
    ms2 = MetricsStore()
    ds2 = Datastore()
    ds2.pool_set(
        EndpointPool(selector={"app": "vllm"}, target_ports=[8000],
                     namespace="default")
    )
    ds2.pod_update_or_add(make_pod(name="h1", ip="10.0.1.2"))
    ms2.update(ds2.endpoints()[0].slot, {Metric.QUEUE_DEPTH: 500})
    picker2 = BatchingTPUPicker(
        sched2, ds2, ms2, max_wait_s=0.002,
        hold_max_s=0.5, hold_queue_limit=100, hold_retry_s=0.01,
    )
    try:
        from gie_tpu.extproc.server import PickRequest

        start = time.monotonic()
        res = picker2.pick(PickRequest(headers={}, body=b"x"), ds2.endpoints())
        elapsed = time.monotonic() - start
        assert res.endpoint == "10.0.1.2:8000"
        assert 0.4 < elapsed < 3.0  # waited ~the deadline, then best-effort
    finally:
        picker2.close()


def test_flow_control_critical_not_held():
    import time

    sched2 = Scheduler(ProfileConfig())
    ms2 = MetricsStore()
    ds2 = Datastore()
    ds2.pool_set(
        EndpointPool(selector={"app": "vllm"}, target_ports=[8000],
                     namespace="default")
    )
    ds2.pod_update_or_add(make_pod(name="h2", ip="10.0.1.3"))
    ms2.update(ds2.endpoints()[0].slot, {Metric.QUEUE_DEPTH: 500})
    picker2 = BatchingTPUPicker(
        sched2, ds2, ms2, max_wait_s=0.002,
        hold_max_s=5.0, hold_queue_limit=100,
    )
    try:
        from gie_tpu.extproc.server import PickRequest

        # Warm pick (also critical — a non-critical warm pick would be
        # HELD against the saturated pool): the first pick pays the
        # multi-second jit compile of the cycle, which is not the claim
        # under test. The TIMED pick below measures the hold decision —
        # a held request waits hold_max_s (5 s); the bound catches that
        # without flaking on compile time under CPU contention.
        picker2.pick(
            PickRequest(headers={mdkeys.OBJECTIVE_KEY: ["critical"]},
                        body=b"x"),
            ds2.endpoints(),
        )
        start = time.monotonic()
        res = picker2.pick(
            PickRequest(headers={mdkeys.OBJECTIVE_KEY: ["critical"]}, body=b"x"),
            ds2.endpoints(),
        )
        assert res.endpoint == "10.0.1.3:8000"
        assert time.monotonic() - start < 2.0  # never held
    finally:
        picker2.close()
