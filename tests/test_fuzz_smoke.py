"""Bounded ASan/UBSan fuzz smoke over the native libraries.

The `make fuzz-smoke` contract as a pytest: build the sanitizer fuzz
binaries (native/fuzz/, standalone driver — docs/ANALYSIS.md), export
the seed corpora from the parity-test bodies, and run each harness for
GIE_FUZZ_SECS seconds (default 30, the acceptance bound; CI can dial it
down). A sanitizer finding aborts the binary non-zero and fails the
test with the tail of its stderr.

Slow tier: four libraries x the budget is ~120 s wall. Tier-1 still
covers the native code through the parity suites (test_fieldscan,
test_promparse_native, test_native, test_extproc_wirelane); this module
is the memory-safety layer on top.
"""

import os
import shutil
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NATIVE = os.path.join(REPO, "native")
FUZZ_SECS = os.environ.get("GIE_FUZZ_SECS", "30")

LIBS = ["jsonscan", "promparse", "chunker", "pbwalk"]


@pytest.fixture(scope="module")
def fuzz_bins():
    if shutil.which("g++") is None:
        pytest.skip("no g++ toolchain for the sanitizer build")
    build = subprocess.run(
        ["make", "-C", NATIVE, "fuzz"], capture_output=True, text=True
    )
    if build.returncode != 0:
        pytest.fail(f"sanitizer fuzz build failed:\n{build.stderr[-2000:]}")
    seeds = subprocess.run(
        [sys.executable, os.path.join(REPO, "hack", "fuzz_seeds.py")],
        capture_output=True, text=True,
    )
    assert seeds.returncode == 0, seeds.stderr
    return os.path.join(NATIVE, "fuzz", "bin")


@pytest.mark.parametrize("lib", LIBS)
def test_fuzz_smoke(fuzz_bins, lib):
    corpus = os.path.join(NATIVE, "fuzz", "corpus", lib)
    assert os.path.isdir(corpus), f"missing corpus {corpus}"
    assert len(os.listdir(corpus)) > 0
    proc = subprocess.run(
        [os.path.join(fuzz_bins, f"fuzz_{lib}"),
         f"-max_total_time={FUZZ_SECS}", "-seed=7", corpus],
        capture_output=True, text=True,
        timeout=int(float(FUZZ_SECS)) * 4 + 120,
    )
    assert proc.returncode == 0, (
        f"fuzz_{lib} found a sanitizer/assert failure:\n"
        f"{proc.stderr[-4000:]}"
    )
    assert "no findings" in proc.stderr
