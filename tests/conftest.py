"""Test configuration: force an 8-device virtual CPU mesh.

Multi-chip TPU hardware is not available in CI; all sharding/collective
behavior is validated on a virtual 8-device CPU platform (the driver
separately dry-run-compiles the multi-chip path via __graft_entry__).
Environment must be set before jax initializes.
"""

import os

# Force-override: the environment pins JAX_PLATFORMS to the axon TPU tunnel,
# but the test tier must run on the virtual CPU mesh.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(0)
