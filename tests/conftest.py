"""Test configuration: force an 8-device virtual CPU mesh.

Multi-chip TPU hardware is not available in CI; all sharding/collective
behavior is validated on a virtual 8-device CPU platform (the driver
separately dry-run-compiles the multi-chip path via __graft_entry__).
Environment must be set before jax initializes.
"""

import os

# Force-override: the environment pins JAX_PLATFORMS to the axon TPU tunnel,
# but the test tier must run on the virtual CPU mesh. The axon
# sitecustomize.py imports jax at interpreter start, so env vars alone are
# too late — update jax.config directly (backends initialize lazily, so this
# still takes effect).
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
assert jax.devices()[0].platform == "cpu", (
    "a JAX backend initialized before conftest could force CPU; "
    "the virtual 8-device mesh tests would silently run on one TPU chip"
)

import subprocess  # noqa: E402

# The native chunker is built on demand (the .so is untracked — a committed
# prebuilt binary can drift from chunker.cc and silently change prefix-cache
# keys). Run make unconditionally: it no-ops when fresh and rebuilds a stale
# binary after chunker.cc edits, so test_native.py always sees the source.
_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(__file__)), "native")
_make = subprocess.run(["make", "-C", _NATIVE_DIR], capture_output=True)
if _make.returncode != 0:
    import warnings

    warnings.warn(
        "native chunker build failed (test_native will skip): "
        + _make.stderr.decode(errors="replace")[-500:]
    )

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: excluded from the tier-1 gate (-m 'not slow'); bounded "
        "multi-stack scenarios like the replication failover test",
    )


@pytest.hookimpl(hookwrapper=True)
def pytest_runtest_makereport(item, call):
    """Failed-test flight-recorder capture (gie-obs, ISSUE 9): when a
    test fails while a FlightRecorder is installed — the chaos-ci
    scenario suite installs one — dump the ring to /tmp/gie-obs so the
    failed scenario explains itself (which endpoints were candidates,
    who was excluded and why, what the data plane did). Best-effort:
    artifact capture must never mask or alter the test outcome."""
    outcome = yield
    rep = outcome.get_result()
    if rep.when == "call" and rep.failed:
        try:
            from gie_tpu import obs

            if obs.RECORDER is not None:
                path = obs.dump_artifact("/tmp/gie-obs", name=item.name)
                if path:
                    item.add_report_section(
                        "call", "flight-recorder",
                        f"decision records dumped to {path}")
        except Exception:
            pass


@pytest.fixture
def rng():
    return np.random.default_rng(0)
