"""Two-EPP failover: the promoted follower takes over WARM.

The scenario the subsystem exists for: two full election+replication
stacks contend on a Lease through the fake apiserver while traffic with
heavy prefix reuse warms the leader's state. The leader is then killed
mid-traffic (crash semantics: renew loop stopped WITHOUT the graceful
release). The follower must win the lease and serve its first waves from
the replicated prefix table — hit-rate within a bound of the dead
leader's — while a cold-takeover control (same traffic, fresh state)
measurably underperforms.

Marked slow (two jit-compiled scheduler stacks + real lease TTL waits);
bounded well under 30s. The tier-1 replication guarantees live in
tests/test_replication.py.
"""

import time

import numpy as np
import pytest

from gie_tpu.controller.kube import KubeClusterClient
from gie_tpu.replication import ReplicationManager, replication_identity
from gie_tpu.runtime.leader import KubeLeaseElector
from gie_tpu.sched import constants as C
from gie_tpu.sched.profile import ProfileConfig, Scheduler
from gie_tpu.utils.testing import make_endpoints, make_requests
from tests.fakeapi import FakeKubeApiServer

NS = "default"
M_SLOTS = 64
WAVE = 8          # requests per wave (N bucket 8)
SESSIONS = 80


def _wait(predicate, timeout_s: float = 6.0) -> bool:
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(0.02)
    return False


def _session_prompt(i: int) -> bytes:
    # ~300 bytes of per-session repeated prefix -> ~5 rolling-hash chunks
    # shared by every request of session i.
    return (b"SESSION %04d CONTEXT " % i) * 15 + b"turn"


def _wave_reqs(session_ids):
    return make_requests(
        len(session_ids),
        prompts=[_session_prompt(i) for i in session_ids],
        m_slots=M_SLOTS)


def _hit_rate(sched: Scheduler, eps, wave_sessions) -> float:
    """Fraction of OK picks that landed on an endpoint the prefix index
    already associates with the request's chain (explain runs the same
    build_stages as the cycle, before the pick's own insert)."""
    hits = total = 0
    for sessions in wave_sessions:
        reqs = _wave_reqs(sessions)
        ex = sched.explain(reqs, eps)
        res = sched.pick(reqs, eps)
        idx = np.asarray(res.indices)[:, 0]
        status = np.asarray(res.status)
        for i in range(len(idx)):
            if status[i] == C.Status.OK and idx[i] >= 0:
                total += 1
                if ex["prefix"][i, idx[i]] > 0.0:
                    hits += 1
    return hits / max(total, 1)


def _groups(lo: int, hi: int):
    ids = list(range(lo, hi))
    return [ids[k:k + WAVE] for k in range(0, len(ids), WAVE)]


class _Stack:
    """One EPP's worth of failover machinery: scheduler + Lease elector +
    replication manager, identity advertising the manager's digest port."""

    def __init__(self, name: str, apiserver):
        self.scheduler = Scheduler(ProfileConfig())
        self.manager = ReplicationManager(
            scheduler=self.scheduler, port=0, interval_s=0.1)
        client = KubeClusterClient(NS, "pool", server=apiserver.url,
                                   token="t")
        self.elector = KubeLeaseElector(
            client, NS, "pool-epp-leader",
            identity=replication_identity(self.manager.advertise, base=name),
            lease_ttl_s=0.6, renew_interval_s=0.08,
            on_role_change=self.manager.on_role_change)
        self.manager.attach_elector(self.elector)

    def start(self):
        self.elector.start()
        self.manager.start()

    def crash(self):
        """Kill the renew loop WITHOUT the graceful release (a crash
        cannot blank the holder) and tear the digest listener down."""
        self.elector._stop.set()
        if self.elector._thread is not None:
            self.elector._thread.join(timeout=2)
        self.manager.stop()

    def stop(self):
        self.manager.stop()
        self.elector.stop()


@pytest.mark.slow
def test_leader_kill_promotes_warm_follower():
    started = time.monotonic()
    api = FakeKubeApiServer()
    a = _Stack("stack-a", api)
    b = _Stack("stack-b", api)
    eps = make_endpoints(
        8, queue=[2.0] * 8, kv=[0.2] * 8, m_slots=M_SLOTS)
    try:
        a.start()
        assert _wait(a.elector.is_leader), "stack A never took the lease"
        b.start()
        time.sleep(0.2)
        assert not b.elector.is_leader(), "two leaders"
        assert a.manager.is_leader() and not b.manager.is_leader()

        # -- warm traffic on the leader: every session inserted ---------
        for sessions in _groups(0, SESSIONS):
            a.scheduler.pick(_wave_reqs(sessions), eps)

        # Pre-failover reference hit-rate over sessions the index knows.
        pre_rate = _hit_rate(a.scheduler, eps, _groups(0, 40))
        assert pre_rate > 0.9, f"leader itself is prefix-cold: {pre_rate}"

        # -- anti-entropy: follower must reach the post-traffic epoch ---
        target_epoch = a.manager.publisher.refresh()
        assert _wait(
            lambda: (b.manager.follower.installed_epoch >= target_epoch),
            timeout_s=8.0,
        ), (
            f"follower never synced epoch {target_epoch} "
            f"(at {b.manager.follower.installed_epoch})")
        assert b.manager.healthy(), "synced follower should report healthy"

        # -- kill the leader mid-traffic --------------------------------
        a.scheduler.pick(_wave_reqs(list(range(8))), eps)  # in-flight wave
        a.crash()
        assert _wait(b.elector.is_leader, timeout_s=6.0), (
            "no takeover after the leader crashed")
        assert b.manager.promoted_with_epoch is not None
        assert b.manager.promoted_with_epoch >= target_epoch

        # -- first waves on the promoted follower -----------------------
        # Sessions 40..79: warmed on A, replicated to B, never re-touched
        # during measurement windows — the takeover must serve them from
        # the transplanted index.
        warm_rate = _hit_rate(b.scheduler, eps, _groups(40, SESSIONS))
        assert warm_rate >= 0.8 * pre_rate, (
            f"warm takeover lost the prefix table: warm {warm_rate:.3f} "
            f"vs pre-failover {pre_rate:.3f}")

        # -- cold-takeover control --------------------------------------
        cold = Scheduler(ProfileConfig())
        cold_rate = _hit_rate(cold, eps, _groups(40, SESSIONS))
        assert cold_rate < warm_rate, (
            f"cold takeover should underperform: cold {cold_rate:.3f} "
            f"vs warm {warm_rate:.3f}")
        assert cold_rate <= 0.5 * warm_rate, (
            f"cold takeover barely underperforms: cold {cold_rate:.3f} "
            f"vs warm {warm_rate:.3f}")
        assert time.monotonic() - started < 30.0, "failover test overran"
    finally:
        b.stop()
        api.close()
