"""Endpoint-axis (M) bucketing: equivalence, state migration, hysteresis.

VERDICT r3 #2: device state and the compiled cycle are sized to the
smallest M bucket covering the live endpoint slots (constants.M_BUCKETS),
so the 256-endpoint north-star shape runs a 256-lane program instead of
M_MAX=1024; beyond M_MAX the datastore degrades to a schedulable subset (test_churn_stress). These tests pin (a) pick equivalence across bucket widths,
(b) state-carrying correctness across grow/shrink migrations (the
reference never resizes — its per-request maps are unbounded; the TPU
design must prove churn across a boundary loses nothing live), and
(c) the batching layer's grow-now/shrink-later hysteresis.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import numpy as np
import pytest

from gie_tpu.sched import constants as C
from gie_tpu.sched.profile import (
    ProfileConfig,
    Scheduler,
    _complete_update,
    scheduling_cycle,
)
from gie_tpu.sched.types import (
    SchedState,
    Weights,
    m_bucket_for,
    resize_state,
)
from gie_tpu.utils.testing import make_endpoints, make_requests


def _cycle(cfg=ProfileConfig()):
    return jax.jit(
        functools.partial(scheduling_cycle, cfg=cfg, predictor_fn=None)
    )


def test_m_bucket_for():
    assert m_bucket_for(1) == C.M_BUCKETS[0]
    assert m_bucket_for(C.M_BUCKETS[0]) == C.M_BUCKETS[0]
    assert m_bucket_for(C.M_BUCKETS[0] + 1) == C.M_BUCKETS[1]
    assert m_bucket_for(C.M_MAX) == C.M_MAX
    with pytest.raises(ValueError):
        m_bucket_for(C.M_MAX + 1)


def test_every_bucket_is_word_aligned():
    for b in C.M_BUCKETS:
        assert b % 32 == 0, "packed prefix words require 32-multiple buckets"
    assert C.M_BUCKETS[-1] == C.M_MAX


@pytest.mark.parametrize("picker", ["topk", "sinkhorn"])
def test_pick_equivalence_across_widths(picker):
    """The same 8 endpoints must produce identical picks whether laid out
    on a 64- or 512-wide axis: padding lanes are masked, never scored."""
    rng = np.random.default_rng(1)
    q = rng.integers(0, 50, 8).tolist()
    kv = rng.uniform(0, 0.9, 8).tolist()
    prompts = [b"SYS %d " % (i % 3) * 8 + b"u%d" % i for i in range(16)]
    cfg = ProfileConfig(picker=picker)
    key = jax.random.PRNGKey(0)
    picks = {}
    for m_slots in (64, 512):
        eps = make_endpoints(8, queue=q, kv=kv, m_slots=m_slots)
        reqs = make_requests(16, prompts=prompts, m_slots=m_slots)
        st = SchedState.init(m=m_slots)
        res, _ = _cycle(cfg)(st, reqs, eps, Weights.default(), key, None)
        picks[m_slots] = (np.asarray(res.indices), np.asarray(res.status))
    if picker == "topk":
        # Deterministic picker: the full fallback lists must be identical.
        assert np.array_equal(picks[64][0], picks[512][0])
    else:
        # Sinkhorn's randomized rounding draws [N, m]-shaped noise, so
        # tie ORDER may differ across widths; the primary pick and status
        # must still agree (same scores, same capacities).
        assert np.array_equal(picks[64][0][:, 0], picks[512][0][:, 0])
    assert np.array_equal(picks[64][1], picks[512][1])


def test_resize_round_trip_preserves_state():
    rng = np.random.default_rng(2)
    eps = make_endpoints(
        8, queue=rng.integers(0, 9, 8).tolist(),
        kv=rng.uniform(0, 0.5, 8).tolist(), m_slots=64)
    prompts = [b"shared system prompt " * 6 + b"u%d" % i for i in range(16)]
    reqs = make_requests(16, prompts=prompts, m_slots=64)
    st = SchedState.init(m=64)
    _, st = _cycle()(st, reqs, eps, Weights.default(), jax.random.PRNGKey(0),
                     None)
    load = np.asarray(st.assumed_load)
    assert load.sum() > 0, "picks must have charged assumed load"

    grown = resize_state(st, 256)
    assert grown.m == 256
    assert np.asarray(grown.prefix.present).shape == (C.PREFIX_SLOTS, 8)
    np.testing.assert_allclose(np.asarray(grown.assumed_load)[:64], load)
    assert np.asarray(grown.assumed_load)[64:].sum() == 0
    # Table keys/ages are m-independent: carried bit-for-bit.
    np.testing.assert_array_equal(
        np.asarray(grown.prefix.keys), np.asarray(st.prefix.keys))

    back = resize_state(grown, 64)
    np.testing.assert_allclose(np.asarray(back.assumed_load), load)
    np.testing.assert_array_equal(
        np.asarray(back.prefix.present), np.asarray(st.prefix.present))


def test_scheduler_migration_keeps_prefix_affinity():
    """Warm cache affinity at the small bucket, churn the pool across the
    boundary: the surviving endpoint's prefix-match column must still score
    after the grow migration."""
    sched = Scheduler()
    q = [5.0] * 8
    kv = [0.3] * 8
    prompts = [b"system prompt alpha " * 8 + b"user %d" % i for i in range(8)]
    eps64 = make_endpoints(8, queue=q, kv=kv, m_slots=64)
    r = sched.pick(make_requests(8, prompts=prompts, m_slots=64), eps64)
    winner = int(np.asarray(r.indices)[0, 0])
    assert winner >= 0
    assert sched.state.m == 64

    # Pool grows past the 64-slot boundary.
    eps256 = make_endpoints(
        100, queue=[5.0] * 100, kv=[0.3] * 100, m_slots=256)
    cols = sched.explain(
        make_requests(4, prompts=prompts[:4], m_slots=256), eps256)
    assert cols["prefix"].shape == (4, 256)
    assert cols["prefix"][:, winner].min() > 0, (
        "prefix affinity recorded before the migration must survive it")

    r2 = sched.pick(make_requests(4, prompts=prompts[:4], m_slots=256),
                    eps256)
    assert sched.state.m == 256
    assert np.asarray(r2.status).max() == int(C.Status.OK)


def test_complete_after_shrink_drops_out_of_range_slot():
    """A request picked before a shrink may complete after it: its charge
    must be dropped, not clamped onto an unrelated slot."""
    st = SchedState.init(m=64)
    st = st.replace(assumed_load=st.assumed_load.at[63].set(2.0))
    out = _complete_update(
        st,
        np.asarray([100, 63], np.int32),   # 100 is beyond the bucket
        np.asarray([1.0, 1.0], np.float32),
    )
    load = np.asarray(out.assumed_load)
    np.testing.assert_allclose(load[63], 1.0)
    assert load.sum() == pytest.approx(1.0)


def test_batching_hysteresis():
    """Grow is immediate; shrink waits for _M_SHRINK_PATIENCE waves."""
    from gie_tpu.sched.batching import BatchingTPUPicker

    @dataclasses.dataclass
    class Ep:
        slot: int

    picker = BatchingTPUPicker.__new__(BatchingTPUPicker)  # no threads
    picker._m_bucket = C.M_BUCKETS[0]
    picker._m_shrink_streak = 0

    assert picker._pick_m_bucket([Ep(3)]) == 64
    assert picker._pick_m_bucket([Ep(70)]) == 256   # grow now
    assert picker._pick_m_bucket([Ep(3)]) == 256    # no instant shrink
    for _ in range(BatchingTPUPicker._M_SHRINK_PATIENCE - 2):
        assert picker._pick_m_bucket([Ep(3)]) == 256
    assert picker._pick_m_bucket([Ep(3)]) == 64     # patience reached
    # A flap during the countdown resets the streak.
    picker._pick_m_bucket([Ep(70)])
    for _ in range(5):
        picker._pick_m_bucket([Ep(3)])
    assert picker._pick_m_bucket([Ep(70)]) == 256
    assert picker._m_shrink_streak == 0


def test_event_ingest_grows_state():
    """KV events for a slot beyond the live bucket grow the state first."""
    sched = Scheduler()
    assert sched.state.m == C.M_BUCKETS[0]
    sched.apply_prefix_events(
        80, stored=np.asarray([7, 9], np.uint32),
        removed=np.zeros((0,), np.uint32))
    assert sched.state.m == 256
    present = np.asarray(sched.state.prefix.present)
    word, bit = 80 // 32, np.uint32(1) << (80 % 32)
    assert (present[:, word] & bit).any()


def test_chunk_bucket_equivalence():
    """Slicing the chunk axis to a bucket covering every request's
    n_chunks must not change any pick: the dropped lanes were masked."""
    from gie_tpu.sched.types import chunk_bucket_for

    rng = np.random.default_rng(3)
    eps = make_endpoints(
        8, queue=rng.integers(0, 9, 8).tolist(),
        kv=rng.uniform(0, 0.5, 8).tolist(), m_slots=64)
    prompts = [b"SYS %d " % (i % 4) * 6 + b"u%d" % i for i in range(16)]
    reqs = make_requests(16, prompts=prompts, m_slots=64)
    cmax = int(np.asarray(reqs.n_chunks).max())
    cb = chunk_bucket_for(cmax)
    assert cb < C.MAX_CHUNKS, "fixture prompts should fit a small bucket"
    sliced = reqs.replace(chunk_hashes=reqs.chunk_hashes[:, :cb])

    key = jax.random.PRNGKey(0)
    results = []
    for r in (reqs, sliced):
        st = SchedState.init(m=64)
        res, st2 = _cycle()(st, r, eps, Weights.default(), key, None)
        results.append((np.asarray(res.indices),
                        np.asarray(st2.assumed_load),
                        np.asarray(st2.prefix.keys)))
    np.testing.assert_array_equal(results[0][0], results[1][0])
    np.testing.assert_allclose(results[0][1], results[1][1])
    # The table state is identical too: lanes beyond n_chunks never
    # inserted anything even at full width.
    np.testing.assert_array_equal(results[0][2], results[1][2])


def test_chunk_bucket_for():
    from gie_tpu.sched.types import chunk_bucket_for

    assert chunk_bucket_for(0) == C.C_BUCKETS[0]
    assert chunk_bucket_for(8) == 8
    assert chunk_bucket_for(9) == 16
    assert chunk_bucket_for(32) == C.MAX_CHUNKS
    assert chunk_bucket_for(99) == C.MAX_CHUNKS  # capped upstream


def test_checkpoint_roundtrip_across_m_buckets(tmp_path):
    """Warm restart saved at a small M bucket restores (the template loop
    tries each bucket) and the next pick migrates it to whatever bucket
    the new pool needs — affinity intact."""
    sched = Scheduler()
    eps = make_endpoints(4, queue=[1.0] * 4, kv=[0.2] * 4, m_slots=64)
    prompt = b"persistent prefix " * 10
    r = sched.pick(make_requests(
        2, prompts=[prompt + b"a", prompt + b"b"], m_slots=64), eps)
    home = int(np.asarray(r.indices)[0, 0])
    assert sched.state.m == 64
    ckpt = str(tmp_path / "m-bucket-state")
    sched.save_state(ckpt)

    s2 = Scheduler()
    assert s2.restore_state(ckpt)
    assert s2.state.m == 64
    # Restart into a BIGGER pool: restore then grow-migrate on pick.
    eps_big = make_endpoints(
        100, queue=[0.5] * 100, kv=[0.2] * 100, m_slots=256)
    r2 = s2.pick(make_requests(
        2, prompts=[prompt + b"c", prompt + b"d"], m_slots=256), eps_big)
    assert s2.state.m == 256
    assert int(np.asarray(r2.indices)[0, 0]) == home, (
        "prefix affinity lost across checkpoint + bucket migration")
