"""Disaggregated prefill/decode scheduling (reference roadmap README.md:115;
role-partitioned candidates anticipated by 006 README:158 — implemented
here as a dual pick in one cycle)."""

import numpy as np
import pytest

from gie_tpu.sched import constants as C
from gie_tpu.sched.profile import (
    ProfileConfig,
    Scheduler,
    pd_costs_host,
)
from gie_tpu.utils.testing import make_endpoints, make_requests

R = C.Role


def _pd_sched(**kw):
    return Scheduler(ProfileConfig(pd_disaggregation=True, **kw))


def test_role_masks_partition_the_dual_pick():
    eps = make_endpoints(
        5, queue=[0, 0, 0, 0, 0],
        role=[R.PREFILL, R.PREFILL, R.DECODE, R.DECODE, R.BOTH],
    )
    s = _pd_sched()
    reqs = make_requests(
        16, prompts=[b"SYS shared " * 20 + b"q%d" % i for i in range(16)])
    res = s.pick(reqs, eps)
    assert (np.asarray(res.status) == C.Status.OK).all()
    assert set(np.asarray(res.prefill)) <= {0, 1, 4}
    assert set(np.asarray(res.indices[:, 0])) <= {2, 3, 4}


@pytest.mark.parametrize("picker", ["topk", "sinkhorn", "random"])
def test_every_picker_supports_pd(picker):
    eps = make_endpoints(4, role=[R.PREFILL, R.PREFILL, R.DECODE, R.DECODE])
    s = _pd_sched(picker=picker)
    res = s.pick(make_requests(8), eps)
    ok = np.asarray(res.status) == C.Status.OK
    assert ok.all()
    assert (np.isin(np.asarray(res.prefill), [0, 1])).all()
    assert (np.isin(np.asarray(res.indices[:, 0]), [2, 3])).all()


def test_missing_role_capacity_is_503():
    s = _pd_sched()
    only_prefill = make_endpoints(2, role=[R.PREFILL, R.PREFILL])
    res = s.pick(make_requests(4), only_prefill)
    assert (np.asarray(res.status) == C.Status.NO_CAPACITY).all()
    assert (np.asarray(res.prefill) == -1).all()
    only_decode = make_endpoints(2, role=[R.DECODE, R.DECODE])
    res = s.pick(make_requests(4), only_decode)
    assert (np.asarray(res.status) == C.Status.NO_CAPACITY).all()


def test_colocation_bonus_prefers_same_endpoint():
    eps = make_endpoints(4, queue=[0, 0, 0, 0])  # all BOTH
    s = _pd_sched(pd_colocation_bonus=5.0)
    res = s.pick(make_requests(8), eps)
    np.testing.assert_array_equal(
        np.asarray(res.prefill), np.asarray(res.indices[:, 0]))


def test_split_load_charging_and_release():
    """Prefill cost lands on the prefill worker, decode cost on the decode
    worker; both match the host-side twins exactly."""
    eps = make_endpoints(2, role=[R.PREFILL, R.DECODE])
    s = _pd_sched(load_decay=1.0, enable_prefix=False)
    reqs = make_requests(1, prompt_len=[4096.0])
    res = s.pick(reqs, eps)
    p, d = int(np.asarray(res.prefill)[0]), int(np.asarray(res.indices[0, 0]))
    assert (p, d) == (0, 1)
    load = s.snapshot_assumed_load()
    p_cost, d_cost = pd_costs_host(4096.0, 0.0)
    assert load[0] == pytest.approx(p_cost)
    assert load[1] == pytest.approx(d_cost)
    # Release both (served feedback path drains exactly what was charged).
    s.complete(np.asarray([p, d], np.int32),
               np.asarray([p_cost, d_cost], np.float32))
    load = s.snapshot_assumed_load()
    assert load[0] == pytest.approx(0.0)
    assert load[1] == pytest.approx(0.0)


def test_prefix_index_tracks_prefill_worker():
    """The prefix cache lives where prefill ran: a second wave with the
    same prompt must send prefill to the SAME prefill worker."""
    eps = make_endpoints(4, role=[R.PREFILL, R.PREFILL, R.DECODE, R.DECODE])
    s = _pd_sched()
    prompt = b"SYSTEM: very long shared system prompt " * 40
    r1 = s.pick(make_requests(1, prompts=[prompt]), eps)
    first = int(np.asarray(r1.prefill)[0])
    for _ in range(3):
        r2 = s.pick(make_requests(1, prompts=[prompt]), eps)
        assert int(np.asarray(r2.prefill)[0]) == first


def test_classic_mode_unchanged():
    """pd off: result carries no prefill field and picks match a scheduler
    that never heard of roles (the default role column is BOTH)."""
    eps = make_endpoints(4, queue=[3, 1, 2, 0])
    plain = Scheduler(ProfileConfig())
    res = plain.pick(make_requests(8), eps)
    assert res.prefill is None


def test_batching_emits_prefill_header_and_releases_both():
    from gie_tpu.api.types import ROLE_LABEL
    from gie_tpu.datastore import Datastore
    from gie_tpu.datastore.objects import EndpointPool, Pod
    from gie_tpu.extproc import metadata as mdkeys
    from gie_tpu.extproc.server import PickRequest
    from gie_tpu.metricsio import MetricsStore
    from gie_tpu.sched.batching import BatchingTPUPicker

    ds = Datastore()
    ds.pool_set(EndpointPool({"app": "x"}, [8000], "default"))
    ds.pod_update_or_add(Pod(
        name="pf0", labels={"app": "x", ROLE_LABEL: "prefill"},
        ip="10.0.0.1"))
    ds.pod_update_or_add(Pod(
        name="dc0", labels={"app": "x", ROLE_LABEL: "decode"},
        ip="10.0.0.2"))
    sched = Scheduler(
        ProfileConfig(pd_disaggregation=True, load_decay=1.0,
                      enable_prefix=False))
    picker = BatchingTPUPicker(sched, ds, MetricsStore(), max_wait_s=0.001)
    try:
        res = picker.pick(
            PickRequest(headers={}, body=b"hello world"), ds.endpoints())
        assert res.endpoint.startswith("10.0.0.2:")       # decode destination
        pf = res.extra_headers[mdkeys.PREFILL_ENDPOINT_KEY]
        assert pf.startswith("10.0.0.1:")
        assert res.charged is not None and len(res.charged) == 2
        # Both charges on device; served feedback releases both.
        assert sched.snapshot_assumed_load().sum() > 0

        class Ctx:
            pick_result = res

        picker.observe_served(res.endpoint, Ctx())
        assert sched.snapshot_assumed_load().sum() == pytest.approx(0.0)
    finally:
        picker.close()


def test_sim_pd_chain_end_to_end():
    """SimCluster executes the full disaggregated chain: prefill job on the
    prefill worker, KV transfer, decode job on the decode worker; user TTFT
    spans the whole chain and stats come out sane."""
    import dataclasses

    from gie_tpu.simulator import StubConfig
    from gie_tpu.simulator.cluster import SimCluster, WorkloadConfig

    stub = StubConfig(max_running=8, prefill_tokens_per_s=4000.0,
                      decode_tokens_per_s=50.0, decode_interference=0.85)
    fleet = ([dataclasses.replace(stub, role="prefill")] * 2
             + [dataclasses.replace(stub, role="decode")] * 2)
    sched = _pd_sched(picker="sinkhorn")
    cluster = SimCluster(n_pods=4, stub_cfg=fleet, seed=0)
    wl = WorkloadConfig(arrival_qps=4.0, n_sessions=64,
                        system_prompt_bytes=256, user_suffix_bytes=8192,
                        decode_tokens_mean=32.0, ttft_slo_s=10.0)
    stats = cluster.run("tpu", wl, duration_s=8.0, scheduler=sched)
    assert stats.completed > 5
    assert stats.goodput_tokens_per_s > 0
    # TTFT includes prefill (8 KB ~ 2048 tokens -> >= 0.5 s at 4000 tok/s).
    assert stats.ttft_p50_s > 0.3
    # Prefill ran ONLY on prefill workers, decode only on decode workers:
    for s in cluster.stubs[2:]:
        # decode pods only ever saw prefill_done jobs: their local prefix
        # caches were never populated.
        assert len(s._prefix) == 0
    for s in cluster.stubs[:2]:
        assert len(s._prefix) > 0


def test_sim_pd_rejects_unmodeled_combos():
    import dataclasses

    import pytest as _pytest

    from gie_tpu.models.latency import LatencyPredictor, OnlineTrainer
    from gie_tpu.simulator import StubConfig
    from gie_tpu.simulator.cluster import SimCluster, WorkloadConfig

    stub = StubConfig()
    fleet = [dataclasses.replace(stub, role="prefill"),
             dataclasses.replace(stub, role="decode")]
    cluster = SimCluster(n_pods=2, stub_cfg=fleet, seed=0)
    with _pytest.raises(ValueError, match="not\\s+modeled"):
        cluster.run("tpu", WorkloadConfig(), duration_s=0.1,
                    scheduler=_pd_sched(),
                    trainer=OnlineTrainer(LatencyPredictor()))


def test_pallas_topk_pd_keeps_colocation_bonus():
    """With use_pallas_topk=True the decode pick must still honor the
    co-location bonus (the fused kernel recomputes the blend and would
    drop it — the decode pick takes the XLA path instead)."""
    eps = make_endpoints(4, queue=[0, 0, 0, 0])  # all BOTH
    s = _pd_sched(pd_colocation_bonus=5.0, use_pallas_topk=True)
    res = s.pick(make_requests(8), eps)
    assert (np.asarray(res.status) == C.Status.OK).all()
    np.testing.assert_array_equal(
        np.asarray(res.prefill), np.asarray(res.indices[:, 0]))


def test_rejected_pd_requests_do_not_pollute_prefix_index():
    """A 503'd dual pick (no decode capacity) must not record its chunks
    as cached on the prefill worker."""
    s = _pd_sched()
    prompt = b"UNIQUE SYSTEM PREAMBLE " * 40
    only_prefill = make_endpoints(2, role=[R.PREFILL, R.PREFILL])
    res = s.pick(make_requests(1, prompts=[prompt]), only_prefill)
    assert int(np.asarray(res.status)[0]) == C.Status.NO_CAPACITY
    # Now add decode capacity; the same prompt has NO recorded affinity,
    # so the prefix column for it must be all-zero (checked via explain).
    full = make_endpoints(4, role=[R.PREFILL, R.PREFILL, R.DECODE, R.DECODE])
    cols = s.explain(make_requests(1, prompts=[prompt]), full)
    assert float(cols["prefix"].max()) == 0.0


def test_locality_only_weights_colocate_decode():
    """Regression (round-4 review): with a locality-only blend (all
    decode-kept weights zero) the decode side has NO signal, so the
    co-location bonus must fully decide the decode pick — float32
    cancellation residue from the incremental de-blend must not outvote
    it and scatter decodes away from the prefill worker."""
    from gie_tpu.sched import Weights

    s = Scheduler(
        ProfileConfig(pd_disaggregation=True),
        weights=_locality_weights(queue=0.0),
    )
    eps = make_endpoints(
        8, queue=[0.0] * 8, kv=[0.1] * 8, role=[R.BOTH] * 8, m_slots=64)
    prompts = [b"shared system prompt " * 10 + b"u%d" % i
               for i in range(16)]
    # Warm the prefix table so the prefill side has real affinity signal.
    s.pick(make_requests(16, prompts=prompts, m_slots=64), eps)
    res = s.pick(make_requests(16, prompts=prompts, m_slots=64), eps)
    prefill = np.asarray(res.prefill)
    decode = np.asarray(res.indices[:, 0])
    ok = prefill >= 0
    assert ok.any()
    np.testing.assert_array_equal(decode[ok], prefill[ok])


def _locality_weights(queue: float):
    from gie_tpu.sched import Weights

    return Weights(
        queue=np.float32(queue), kv_cache=np.float32(0.0),
        prefix=np.float32(7.7), lora=np.float32(0.0),
        assumed_load=np.float32(0.0), latency=np.float32(0.0),
        session=np.float32(2.2),
    )


def test_small_but_legit_decode_weight_is_honored():
    """The degeneracy guard must not discard a deliberately small decode
    weight: queue=0.008 against a ~10-mass locality blend is 0.08% of
    the total — above the 1e-4 relative threshold — so the decode pick
    must still prefer the emptier queue, not fall back to co-location."""
    s = Scheduler(
        ProfileConfig(pd_disaggregation=True, pd_colocation_bonus=0.0),
        weights=_locality_weights(queue=0.008),
    )
    # Decode workers: slot 2 idle, slot 3 loaded. Prefill workers 0/1.
    eps = make_endpoints(
        4, queue=[0.0, 0.0, 0.0, 60.0], kv=[0.1] * 4,
        role=[R.PREFILL, R.PREFILL, R.DECODE, R.DECODE],
        m_slots=64)
    prompts = [b"shared system prompt " * 10 + b"u%d" % i for i in range(8)]
    res = s.pick(make_requests(8, prompts=prompts, m_slots=64), eps)
    decode = np.asarray(res.indices[:, 0])
    ok = decode >= 0
    assert ok.any()
    assert (decode[ok] == 2).all(), (
        f"small queue weight silently zeroed: decode picks {decode}")
