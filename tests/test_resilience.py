"""Unified resilience layer tests (ISSUE 7, docs/RESILIENCE.md).

Covers: seeded fault-injection determinism (bit-identical schedules per
seed, per-(point, key) stream independence under thread interleaving),
the shared backoff policy's parity with the three hand-rolled copies it
replaced (replication follower, scrape engine, autoscale actuator),
circuit-breaker state transitions, deadline-header propagation and
shedding, the degradation ladder's descent/hysteretic-ascent semantics,
and the woven call sites: degraded picks on dispatch/materialize
failure, breaker candidate filtering, queue-deadline shedding, the
actuator's retried patch, the native-scan fallback, the follower's
poll fault, and the publisher's corrupt frame against the codec CRC.
"""

from __future__ import annotations

import random
import threading
import time

import grpc
import numpy as np
import pytest

from gie_tpu.resilience import faults
from gie_tpu.resilience.breaker import (
    BreakerBoard, BreakerConfig, BreakerState, CircuitBreaker)
from gie_tpu.resilience.deadline import (
    DeadlineExceeded, deadline_from_headers, expired, remaining_s)
from gie_tpu.resilience.faults import FaultError, FaultInjector, FaultRule
from gie_tpu.resilience.ladder import (
    DegradationLadder, LadderConfig, ResilienceState, Rung)
from gie_tpu.resilience.policy import (
    JITTER_SYMMETRIC, Backoff, BackoffPolicy, retry_call)


@pytest.fixture(autouse=True)
def _no_leaked_injector():
    """Every test starts and ends with injection disarmed."""
    faults.uninstall()
    yield
    faults.uninstall()


# --------------------------------------------------------------------------
# Fault injection: determinism
# --------------------------------------------------------------------------


def test_injector_rejects_unknown_point():
    with pytest.raises(ValueError, match="unknown fault point"):
        FaultInjector(1, {"not.a.point": FaultRule(p_error=1.0)})
    with pytest.raises(ValueError, match="probabilities"):
        FaultRule(p_error=0.9, p_latency=0.9)


def _draw_schedule(seed: int, n: int, keys: list) -> dict:
    """Per-(point, key) verdict sequences with draws interleaved across
    threads in a key-dependent order — the determinism contract is that
    interleaving cannot perturb any single stream."""
    inj = FaultInjector(seed, {
        "scrape.fetch": FaultRule(p_error=0.3, p_latency=0.2,
                                  latency_s=0.0),
    })
    out = {k: [] for k in keys}
    lock = threading.Lock()

    def worker(key):
        seq = []
        for _ in range(n):
            seq.append(inj.verdict("scrape.fetch", key).kind)
        with lock:
            out[key] = seq

    threads = [threading.Thread(target=worker, args=(k,)) for k in keys]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return out


def test_same_seed_bit_identical_schedule_across_interleavings():
    keys = [f"http://10.0.0.{i}:8000/metrics" for i in range(6)]
    a = _draw_schedule(7, 200, keys)
    b = _draw_schedule(7, 200, list(reversed(keys)))  # different order
    assert a == b
    # A different seed produces a different schedule (200 draws at 50%
    # fault mass collide with probability ~0).
    c = _draw_schedule(8, 200, keys)
    assert a != c
    # And faults actually fired (not vacuous all-ok equality).
    assert any(k != "ok" for seq in a.values() for k in seq)


def test_streams_independent_across_keys():
    """Adding traffic on key B must not perturb key A's stream."""
    inj1 = FaultInjector(3, {"scrape.fetch": FaultRule(p_error=0.5)})
    solo = [inj1.verdict("scrape.fetch", "A").kind for _ in range(50)]
    inj2 = FaultInjector(3, {"scrape.fetch": FaultRule(p_error=0.5)})
    mixed = []
    for i in range(50):
        mixed.append(inj2.verdict("scrape.fetch", "A").kind)
        inj2.verdict("scrape.fetch", "B")  # interloper
    assert solo == mixed


def test_rule_after_and_max_fires_and_keys():
    inj = FaultInjector(1, {"scrape.fetch": FaultRule(
        p_error=1.0, after=3, max_fires=2, keys=("target",))})
    # Non-matching key: never fires.
    assert inj.verdict("scrape.fetch", "other").kind == "ok"
    kinds = [inj.verdict("scrape.fetch", "target-1").kind
             for _ in range(8)]
    # 3 warmup oks, then exactly max_fires errors, then quiet.
    assert kinds == ["ok"] * 3 + ["error"] * 2 + ["ok"] * 3
    assert inj.fired == {"scrape.fetch": 2}
    assert len(inj.log) == 2


def test_check_raises_fault_error_as_connection_error():
    faults.install(FaultInjector(
        1, {"kube.patch": FaultRule(p_error=1.0)}))
    with pytest.raises(ConnectionError) as exc:
        faults.check("kube.patch", key="deploy/pool")
    assert isinstance(exc.value, FaultError)
    assert exc.value.point == "kube.patch"
    faults.uninstall()
    assert not faults.ENABLED
    # Disarmed: fire() is a no-op OK.
    assert faults.fire("kube.patch").kind == "ok"


def test_parse_spec():
    rules = faults.parse_spec(
        ["scrape.fetch=error:0.2,latency:0.1:80ms",
         "endpoint.hang=hang:0.05:2.5"])
    r = rules["scrape.fetch"]
    assert r.p_error == 0.2 and r.p_latency == 0.1
    assert r.latency_s == pytest.approx(0.08)
    assert rules["endpoint.hang"].hang_s == pytest.approx(2.5)
    for bad in ["nope=error:1.0", "scrape.fetch", "scrape.fetch=error",
                "scrape.fetch=explode:1.0"]:
        with pytest.raises(ValueError):
            faults.parse_spec([bad])


# --------------------------------------------------------------------------
# Backoff policy: shape + parity with the replaced hand-rolled copies
# --------------------------------------------------------------------------


def test_backoff_shape_cap_and_reset():
    b = Backoff(BackoffPolicy(base_s=0.1, max_s=1.0, jitter=0.0))
    assert b.ok() == pytest.approx(0.1)
    assert [b.fail() for _ in range(6)] == pytest.approx(
        [0.2, 0.4, 0.8, 1.0, 1.0, 1.0])
    assert b.failures == 6  # streak keeps counting past the cap
    assert b.ok() == pytest.approx(0.1) and b.failures == 0


def test_backoff_exponent_cap_never_overflows():
    b = Backoff(BackoffPolicy(base_s=0.01, max_s=1.0, jitter=0.0,
                              max_exponent=20))
    b.failures = 5000  # a pod down for hours
    assert np.isfinite(b.raw_delay()) and b.raw_delay() == 1.0


def test_backoff_policy_validation():
    with pytest.raises(ValueError):
        BackoffPolicy(base_s=-1.0, max_s=1.0)
    with pytest.raises(ValueError):
        BackoffPolicy(base_s=2.0, max_s=1.0)
    with pytest.raises(ValueError):
        BackoffPolicy(base_s=0.1, max_s=1.0, factor=1.0)
    with pytest.raises(ValueError):
        BackoffPolicy(base_s=0.1, max_s=1.0, jitter_mode="nope")


def test_follower_backoff_parity_with_hand_rolled():
    """The exact delay sequence the follower's hand-rolled code produced
    (double-from-base, cap, upward jitter from a seeded RNG), over a
    mixed fail/ok pattern."""
    interval, bmax, jitter, seed = 0.25, 8.0, 0.25, 42
    pattern = [True, True, True, True, True, True, False, True, False,
               False, True, True, True, True, True, True, True]

    # Verbatim reimplementation of the replaced _schedule arithmetic.
    rng_old = random.Random(seed)
    backoff, old = interval, []
    for failed in pattern:
        if failed:
            backoff = min(max(backoff, interval) * 2.0, bmax)
        else:
            backoff = interval
        old.append(backoff * (1.0 + jitter * rng_old.random()))

    rng_new = random.Random(seed)
    b = Backoff(BackoffPolicy(base_s=interval, max_s=bmax, jitter=jitter),
                rng=rng_new)
    new = [b.fail() if failed else b.ok() for failed in pattern]
    assert new == pytest.approx(old)


def test_engine_backoff_parity_with_hand_rolled():
    """The exact delay sequence the scrape engine's hand-rolled code
    produced (streak exponent capped at 20, symmetric jitter, max_s
    ceiling, snap back on success)."""
    interval, bmax, jitter, seed = 0.05, 1.0, 0.1, 9

    rng_old = random.Random(seed)
    streak, old = 0, []
    pattern = [True] * 25 + [False] + [True] * 3
    for failed in pattern:
        if failed:
            streak += 1
            raw = min(interval * (2.0 ** min(streak, 20)), bmax)
        else:
            streak = 0
            raw = interval
        old.append(raw * (1.0 + rng_old.uniform(-jitter, jitter)))

    b = Backoff(
        BackoffPolicy(base_s=interval, max_s=bmax, jitter=jitter,
                      jitter_mode=JITTER_SYMMETRIC, max_exponent=20),
        rng=random.Random(seed))
    new = [b.fail() if failed else b.ok() for failed in pattern]
    assert new == pytest.approx(old)


def test_retry_call_retries_then_succeeds_and_then_raises():
    calls, slept = [], []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise ConnectionError("nope")
        return "done"

    pol = BackoffPolicy(base_s=0.1, max_s=1.0, jitter=0.0)
    assert retry_call(flaky, pol, attempts=3,
                      sleep=slept.append) == "done"
    assert len(calls) == 3
    assert slept == pytest.approx([0.2, 0.4])  # policy-shaped delays

    def always():
        raise ConnectionError("still no")

    with pytest.raises(ConnectionError):
        retry_call(always, pol, attempts=2, sleep=slept.append)
    with pytest.raises(ValueError):
        retry_call(always, pol, attempts=0)


# --------------------------------------------------------------------------
# Circuit breaker
# --------------------------------------------------------------------------


class _Clock:
    def __init__(self):
        self.t = 100.0

    def __call__(self):
        return self.t


def test_breaker_open_halfopen_close_cycle():
    clk = _Clock()
    b = CircuitBreaker(BreakerConfig(open_after=3, open_s=2.0,
                                     close_after=2), clock=clk)
    for _ in range(2):
        b.record(False)
    assert b.state == BreakerState.CLOSED  # streak below threshold
    b.record(True)
    b.record(False); b.record(False)
    assert b.state == BreakerState.CLOSED  # success reset the streak
    b.record(False)
    assert b.state == BreakerState.OPEN
    assert not b.allow()                   # dwell: no probes yet
    clk.t += 2.5
    assert b.allow()                       # dwell over -> HALF_OPEN probe
    assert b.state == BreakerState.HALF_OPEN
    b.record(False)                        # probe failed
    assert b.state == BreakerState.OPEN and not b.allow()
    clk.t += 2.5
    assert b.allow()
    b.record(True)
    assert b.state == BreakerState.HALF_OPEN  # hysteresis: one is not enough
    b.record(True)
    assert b.state == BreakerState.CLOSED


def test_breaker_board_has_open_flag_and_drop():
    clk = _Clock()
    board = BreakerBoard(BreakerConfig(open_after=2, open_s=60.0),
                         clock=clk)
    board.record(3, True)      # healthy unknown endpoint: not tracked
    assert not board.has_open and board.states() == {}
    board.record(3, False); board.record(3, False)
    assert board.has_open and not board.allow(3)
    assert board.allow(4)      # unknown slots flow freely
    assert board.states() == {3: BreakerState.OPEN}
    assert board.open_count() == 1
    board.drop(3)              # evicted endpoint: history must not survive
    assert not board.has_open and board.allow(3)
    assert board.state(3) == BreakerState.CLOSED


# --------------------------------------------------------------------------
# Deadline propagation
# --------------------------------------------------------------------------


def test_deadline_header_parsing_and_precedence():
    now = 1000.0
    # Envoy's route timeout alone.
    d = deadline_from_headers(
        {"x-envoy-expected-rq-timeout-ms": ["2000"]}, now=now)
    assert d == pytest.approx(now + 2.0)
    # The caller-pinned gateway deadline wins over Envoy's.
    d = deadline_from_headers(
        {"x-gateway-request-deadline-ms": ["500"],
         "x-envoy-expected-rq-timeout-ms": ["2000"]}, now=now)
    assert d == pytest.approx(now + 0.5)
    # Garbage / non-positive / NaN / sub-ms budgets -> no deadline.
    for bad in (["nope"], ["-5"], ["0"], ["nan"], ["0.5"]):
        assert deadline_from_headers(
            {"x-gateway-request-deadline-ms": bad}, now=now) == 0.0
    assert deadline_from_headers({}, now=now) == 0.0
    # A hostile 1e308 header is clamped, never an inf deadline.
    d = deadline_from_headers(
        {"x-gateway-request-deadline-ms": ["1e308"]}, now=now)
    assert np.isfinite(d) and d <= now + 3600.0


def test_remaining_and_expired():
    assert remaining_s(0.0) == float("inf")
    assert not expired(0.0)
    now = time.monotonic()
    assert remaining_s(now + 5.0, now=now) == pytest.approx(5.0)
    assert expired(now - 0.1, now=now)
    assert not expired(now + 5.0, now=now)


# --------------------------------------------------------------------------
# Degradation ladder
# --------------------------------------------------------------------------


def _ladder(clk, **kw):
    cfg = dict(dispatch_error_streak=3, blackout_stale_s=5.0,
               latency_breach_s=1.0, latency_breach_streak=4,
               recover_streak=2, min_dwell_s=2.0, probe_interval_s=1.0)
    cfg.update(kw)
    return DegradationLadder(LadderConfig(**cfg), clock=clk)


def test_ladder_descends_on_error_streak_and_recovers_hysteretically():
    clk = _Clock()
    lad = _ladder(clk)
    changes = []
    lad.on_change = changes.append
    for _ in range(2):
        lad.note_dispatch_error()
    assert lad.rung() == Rung.FULL          # streak below threshold
    lad.note_dispatch_error()
    assert lad.rung() == Rung.CACHED
    # Another full streak descends further (probe waves keep failing).
    for _ in range(3):
        lad.note_dispatch_error()
    assert lad.rung() == Rung.ROUND_ROBIN
    # Ascent needs BOTH a success streak and the minimum dwell.
    lad.note_dispatch_ok(); lad.note_dispatch_ok()
    assert lad.rung() == Rung.ROUND_ROBIN   # dwell not served yet
    clk.t += 3.0
    lad.note_dispatch_ok(); lad.note_dispatch_ok()
    assert lad.rung() == Rung.CACHED
    clk.t += 3.0
    lad.note_dispatch_ok(); lad.note_dispatch_ok()
    assert lad.rung() == Rung.FULL
    assert changes == [1, 2, 1, 0]
    # The transition trace records every effective-rung flip.
    assert [r for _, r in lad.transitions] == [1, 2, 1, 0]


def test_ladder_error_streak_broken_by_success():
    clk = _Clock()
    lad = _ladder(clk)
    lad.note_dispatch_error(); lad.note_dispatch_error()
    lad.note_dispatch_ok()
    lad.note_dispatch_error(); lad.note_dispatch_error()
    assert lad.rung() == Rung.FULL


def test_ladder_latency_breach_moves_to_cached():
    clk = _Clock()
    lad = _ladder(clk)
    for _ in range(3):
        lad.note_dispatch_ok(latency_s=2.0)
    assert lad.rung() == Rung.FULL
    lad.note_dispatch_ok(latency_s=2.0)     # 4th consecutive slow pick
    assert lad.rung() == Rung.CACHED
    # A fast pick resets the slow streak while degraded.
    clk.t += 3.0
    lad.note_dispatch_ok(latency_s=0.1); lad.note_dispatch_ok(latency_s=0.1)
    assert lad.rung() == Rung.FULL


def test_ladder_slow_probes_do_not_count_toward_recovery():
    """A latency-breaching probe is NOT a recovery signal: a device that
    answers every probe slowly must STAY degraded — counting slow probes
    toward the ascent streak would oscillate FULL <-> CACHED forever."""
    clk = _Clock()
    lad = _ladder(clk, dispatch_error_streak=1, min_dwell_s=0.0)
    lad.note_dispatch_error()
    assert lad.rung() == Rung.CACHED
    for _ in range(10):                      # every probe breaches
        clk.t += 1.0
        lad.note_dispatch_ok(latency_s=5.0)
        assert lad.rung() == Rung.CACHED, "slow probes must not climb"
    # Genuinely fast probes still climb.
    lad.note_dispatch_ok(latency_s=0.1)
    clk.t += 1.0
    lad.note_dispatch_ok(latency_s=0.1)
    assert lad.rung() == Rung.FULL


def test_ladder_blackout_floor_and_hysteretic_lift():
    clk = _Clock()
    lad = _ladder(clk)
    lad.note_metrics_staleness(6.0)
    assert lad.rung() == Rung.ROUND_ROBIN   # blackout floors at RR
    # Staleness back under the threshold but above the recovery
    # fraction: the floor must HOLD (hysteresis).
    lad.note_metrics_staleness(4.0)
    assert lad.rung() == Rung.ROUND_ROBIN
    lad.note_metrics_staleness(1.0)         # < 5.0 * 0.5
    assert lad.rung() == Rung.FULL


def test_ladder_effective_rung_is_max_of_level_and_floor():
    clk = _Clock()
    lad = _ladder(clk)
    for _ in range(3):
        lad.note_dispatch_error()           # level = CACHED
    lad.note_metrics_staleness(6.0)         # floor = ROUND_ROBIN
    assert lad.rung() == Rung.ROUND_ROBIN
    lad.note_metrics_staleness(1.0)         # floor lifts
    assert lad.rung() == Rung.CACHED        # level remains
    rep = lad.report()
    assert rep["rung_name"] == "CACHED" and rep["blackout_floor"] == 0


def test_ladder_probe_cadence():
    clk = _Clock()
    lad = _ladder(clk)
    assert not lad.should_probe()           # FULL: probes are meaningless
    for _ in range(3):
        lad.note_dispatch_error()
    assert lad.should_probe()               # first probe immediately
    assert not lad.should_probe()           # then at probe_interval_s
    clk.t += 1.1
    assert lad.should_probe()


def test_resilience_state_report_and_broken_staleness_source():
    rs = ResilienceState(staleness_fn=lambda: 1 / 0, on_change=lambda r: None)
    rs.observe()                            # must not raise
    assert rs.healthy()
    rs.board.record(2, False)
    for _ in range(4):
        rs.board.record(2, False)
    assert not rs.healthy()
    rep = rs.report()
    assert rep["breakers_open"] == 1 and rep["rung"] == 0


# --------------------------------------------------------------------------
# Woven call sites: picker (degraded picks, deadline shed, breaker filter)
# --------------------------------------------------------------------------

from gie_tpu.datastore import Datastore                      # noqa: E402
from gie_tpu.datastore.objects import EndpointPool, Pod      # noqa: E402
from gie_tpu.extproc.server import ExtProcError, PickRequest  # noqa: E402
from gie_tpu.metricsio import MetricsStore                   # noqa: E402
from gie_tpu.sched import ProfileConfig, Scheduler           # noqa: E402
from gie_tpu.sched.batching import BatchingTPUPicker         # noqa: E402


def _stack(n_pods=2, resilience=None, **picker_kw):
    sched = Scheduler(ProfileConfig(load_decay=1.0))
    ms = MetricsStore()
    ds = Datastore(on_slot_reclaimed=lambda s: (sched.evict_endpoint(s),
                                                ms.remove(s)))
    ds.pool_set(EndpointPool({"app": "x"}, [8000], "default"))
    for i in range(n_pods):
        ds.pod_update_or_add(
            Pod(name=f"p{i}", labels={"app": "x"}, ip=f"10.9.0.{i + 1}"))
    picker = BatchingTPUPicker(sched, ds, ms, max_wait_s=0.02,
                               resilience=resilience, **picker_kw)
    return sched, ds, ms, picker


def test_dispatch_failure_serves_degraded_instead_of_failing():
    rs = ResilienceState(on_change=lambda r: None)
    sched, ds, ms, picker = _stack(resilience=rs)
    try:
        def boom(*a, **kw):
            raise RuntimeError("device dispatch failed")
        picker.scheduler = _SchedProxy(sched, boom)
        results = [picker.pick(PickRequest(headers={}, body=b"x"),
                               ds.endpoints()) for _ in range(6)]
        assert all(":" in r.endpoint for r in results)
        # Nothing was charged: degraded picks must not leak assumed load.
        assert all(r.charged_slot == -1 for r in results)
        assert rs.ladder.rung() >= Rung.CACHED
    finally:
        picker.close()


def test_dispatch_failure_without_resilience_keeps_seed_behavior():
    sched, ds, ms, picker = _stack(resilience=None)
    try:
        def boom(*a, **kw):
            raise RuntimeError("device dispatch failed")
        picker.scheduler = _SchedProxy(sched, boom)
        with pytest.raises(ExtProcError):
            picker.pick(PickRequest(headers={}, body=b"x"), ds.endpoints())
    finally:
        picker.close()


class _SchedProxy:
    """Scheduler wrapper overriding pick_async only."""

    def __init__(self, real, pick_async):
        self._real = real
        self.pick_async = pick_async

    def __getattr__(self, name):
        return getattr(self._real, name)


def test_materialize_failure_serves_degraded():
    rs = ResilienceState(on_change=lambda r: None)
    sched, ds, ms, picker = _stack(resilience=rs)
    try:
        class _BadPending:
            def materialize(self):
                raise RuntimeError("device died mid-cycle")
        picker.scheduler = _SchedProxy(
            sched, lambda *a, **kw: _BadPending())
        res = picker.pick(PickRequest(headers={}, body=b"x"),
                          ds.endpoints())
        assert ":" in res.endpoint and res.charged_slot == -1
    finally:
        picker.close()


def test_queue_deadline_shed_503():
    sched, ds, ms, picker = _stack()
    try:
        req = PickRequest(headers={}, body=b"x",
                          deadline_at=time.monotonic() - 0.1)
        with pytest.raises(DeadlineExceeded):
            picker.pick(req, ds.endpoints())
        # A deadline safely in the future schedules normally.
        ok = picker.pick(
            PickRequest(headers={}, body=b"y",
                        deadline_at=time.monotonic() + 60.0),
            ds.endpoints())
        assert ":" in ok.endpoint
    finally:
        picker.close()


def test_breaker_filter_avoids_quarantined_endpoint():
    rs = ResilienceState(on_change=lambda r: None)
    sched, ds, ms, picker = _stack(resilience=rs)
    try:
        eps = ds.endpoints()
        sick = eps[0]
        for _ in range(5):
            rs.board.record(sick.slot, False)
        assert rs.board.has_open
        healthy_hostports = {e.hostport for e in eps if e.slot != sick.slot}
        for _ in range(4):
            res = picker.pick(PickRequest(headers={}, body=b"x"),
                              ds.endpoints())
            assert res.endpoint in healthy_hostports
            assert sick.hostport not in [res.endpoint] + res.fallbacks
    finally:
        picker.close()


def test_degraded_rungs_round_robin_and_static():
    """Force the ladder floor and assert each rung serves and spreads."""
    rs = ResilienceState(on_change=lambda r: None, static_subset=2)
    sched, ds, ms, picker = _stack(n_pods=4, resilience=rs)
    try:
        rs.ladder.note_metrics_staleness(100.0)   # blackout -> RR floor
        assert rs.ladder.rung() == Rung.ROUND_ROBIN
        picked = [picker.pick(PickRequest(headers={}, body=b"x"),
                              ds.endpoints()).endpoint for _ in range(8)]
        assert len(set(picked)) > 1               # genuinely rotates
        # STATIC floor: descend the level component all the way down.
        for _ in range(20):
            rs.ladder.note_dispatch_error()
        assert rs.ladder.rung() == Rung.STATIC
        # Consume the immediate full-path probe the level descent arms —
        # this phase asserts the DEGRADED picks' subset discipline.
        rs.ladder.should_probe()
        picked = [picker.pick(PickRequest(headers={}, body=b"x"),
                              ds.endpoints()).endpoint for _ in range(8)]
        live = sorted(e.slot for e in ds.endpoints())
        subset = {e.hostport for e in ds.endpoints()
                  if e.slot in live[:2]}
        assert set(picked) <= subset              # fixed 2-endpoint subset
        assert len(set(picked)) == 2              # rotation inside it
    finally:
        picker.close()


# --------------------------------------------------------------------------
# Woven call sites: actuator, fieldscan, follower, publisher, engine
# --------------------------------------------------------------------------


def test_actuator_retries_transient_patch_failures():
    from gie_tpu.autoscale.actuator import ReplicaActuator
    from gie_tpu.autoscale.recommender import Recommendation

    calls = []

    class _Client:
        def _json(self, method, path, body=None, content_type=None):
            calls.append(method)
            if len(calls) < 3:
                raise ConnectionError("apiserver blip")
            return {}

    act = ReplicaActuator(_Client(), "default", target="pool")
    rec = Recommendation(at=0.0, current=2, desired=3, reason="test")
    assert act.apply(rec) == "patched"
    assert len(calls) == 3                  # two blips absorbed in-call


def test_actuator_kube_patch_fault_degrades_to_error():
    from gie_tpu.autoscale.actuator import ReplicaActuator
    from gie_tpu.autoscale.recommender import Recommendation

    class _Client:
        def _json(self, *a, **kw):
            raise AssertionError("patch must be intercepted by the fault")

    faults.install(FaultInjector(
        5, {"kube.patch": FaultRule(p_error=1.0)}))
    act = ReplicaActuator(_Client(), "default", target="pool")
    assert act.apply(Recommendation(at=0.0, current=2, desired=3,
                                    reason="test")) == "error"
    # All three attempts drew (and hit) the injected outage.
    assert faults.installed().fired["kube.patch"] == 3


def test_fieldscan_native_scan_fault_falls_back_to_python():
    from gie_tpu.extproc import fieldscan

    body = b'{"model": "m1", "stream": true, "max_tokens": 7}'
    want = fieldscan.scan_py(body)
    faults.install(FaultInjector(
        2, {"native.scan": FaultRule(p_error=1.0)}))
    got = fieldscan.scan(body)              # fault -> python fallback
    assert got == want
    faults.uninstall()
    assert fieldscan.scan(body) == want     # and identical when healthy


def test_follower_poll_fault_is_absorbed_as_fetch_error():
    from gie_tpu.replication import FollowerSync, StatePublisher
    from gie_tpu.replication import follower as fol_mod

    pub = StatePublisher({"s": lambda: {"x": np.ones(2)}}, era="e")
    pub.refresh()

    def mem_fetch(base, since, era, etag):
        return pub.serve(since=since, era=era, if_none_match=etag)

    fol = FollowerSync(lambda: "mem://", lambda s, delta: True,
                       interval_s=0.0, fetch=mem_fetch, seed=1)
    faults.install(FaultInjector(
        4, {"replication.poll": FaultRule(p_error=1.0, max_fires=2)}))
    assert fol.poll_once() == fol_mod.FETCH_ERROR
    assert fol.poll_once() == fol_mod.FETCH_ERROR
    assert fol.fetch_errors == 2
    # Partition heals (max_fires exhausted): the next poll installs.
    assert fol.poll_once() == fol_mod.INSTALLED
    assert fol.installed_epoch == 1


def test_publisher_corrupt_frame_rejected_by_codec_crc():
    from gie_tpu.replication import FollowerSync, StatePublisher
    from gie_tpu.replication import follower as fol_mod

    pub = StatePublisher({"s": lambda: {"x": np.arange(8.0)}}, era="e")
    pub.refresh()

    def mem_fetch(base, since, era, etag):
        return pub.serve(since=since, era=era, if_none_match=etag)

    installed = {}

    def install(sections, *, delta):
        installed.update(sections)
        return True

    fol = FollowerSync(lambda: "mem://", install, interval_s=0.0,
                       fetch=mem_fetch, seed=1)
    faults.install(FaultInjector(
        6, {"replication.publish": FaultRule(p_corrupt=1.0,
                                             max_fires=1)}))
    # The corrupted frame must be rejected (CRC), never installed.
    assert fol.poll_once() == fol_mod.CORRUPT
    assert fol.installed_epoch == 0 and not installed
    # Next poll serves clean bytes and installs.
    assert fol.poll_once() == fol_mod.INSTALLED
    assert np.array_equal(installed["s"]["x"], np.arange(8.0))


def test_engine_scrape_fault_feeds_breakers():
    from gie_tpu.metricsio.engine import ScrapeEngine
    from gie_tpu.metricsio.mappings import VLLM
    from tests.test_metricsio_sim import VLLM_TEXT

    board = BreakerBoard(BreakerConfig(open_after=3, open_s=60.0))
    store = MetricsStore()
    sick_url = "http://10.2.0.1:8000/metrics"
    ok_url = "http://10.2.0.2:8000/metrics"
    faults.install(FaultInjector(11, {
        "scrape.fetch": FaultRule(p_error=1.0, keys=("10.2.0.1",)),
    }))
    eng = ScrapeEngine(store, interval_s=0.01, fetcher=lambda u: VLLM_TEXT,
                       workers=1, breaker_board=board)
    try:
        eng.attach(0, sick_url, VLLM)
        eng.attach(1, ok_url, VLLM)
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and not board.has_open:
            time.sleep(0.01)
        assert board.state(0) == BreakerState.OPEN
        assert board.state(1) == BreakerState.CLOSED
        assert store._has_data[1]          # the healthy endpoint scraped
        # Detach drops the breaker history with the endpoint.
        eng.detach(0)
        deadline = time.monotonic() + 2.0
        while time.monotonic() < deadline and board.has_open:
            time.sleep(0.01)
        assert board.state(0) == BreakerState.CLOSED
    finally:
        eng.close()
