"""Datastore + reconciler tests (reference datastore_test.go /
pod_reconciler_test.go behavioral coverage + TPU slot-lifecycle additions)."""

import threading

import pytest

from gie_tpu.api import types as api
from gie_tpu.controller import (
    FakeCluster,
    InferencePoolReconciler,
    PodReconciler,
    RequeueAfter,
)
from gie_tpu.controller.reconcilers import wire
from gie_tpu.datastore import Datastore, Pod, PoolNotSyncedError
from gie_tpu.datastore.objects import EndpointPool
from gie_tpu.utils.kubemeta import GKNN


POOL = EndpointPool(
    selector={"app": "vllm"}, target_ports=[8000, 8002], namespace="default"
)


def make_pod(name="p1", ip="10.0.0.1", labels=None, annotations=None, ready=True):
    return Pod(
        name=name,
        namespace="default",
        labels=labels if labels is not None else {"app": "vllm"},
        annotations=annotations or {},
        ip=ip,
        ready=ready,
    )


def test_pool_required_before_pods():
    ds = Datastore()
    with pytest.raises(PoolNotSyncedError):
        ds.pod_update_or_add(make_pod())
    assert not ds.pool_has_synced()


def test_rank_endpoints_per_target_port():
    """One endpoint per (pod, rank) named <pod>-rank-<idx>
    (reference datastore.go:329-334, DP-rank semantics SURVEY 2.10)."""
    ds = Datastore()
    ds.pool_set(POOL)
    ds.pod_update_or_add(make_pod())
    eps = ds.endpoints()
    assert sorted(e.name for e in eps) == ["p1-rank-0", "p1-rank-1"]
    assert sorted(e.port for e in eps) == [8000, 8002]
    assert len({e.slot for e in eps}) == 2


def test_active_ports_annotation_filters_ranks():
    """reference datastore.go:307-325: comma-separated allowlist restricted
    to pool targetPorts."""
    ds = Datastore()
    ds.pool_set(POOL)
    ds.pod_update_or_add(
        make_pod(annotations={api.ACTIVE_PORTS_ANNOTATION: " 8000 , 9999, x"})
    )
    eps = ds.endpoints()
    assert [e.port for e in eps] == [8000]
    # Annotation change re-activates the other rank.
    ds.pod_update_or_add(
        make_pod(annotations={api.ACTIVE_PORTS_ANNOTATION: "8000,8002"})
    )
    assert len(ds.endpoints()) == 2


def test_slot_reclaim_callback_on_delete():
    reclaimed = []
    ds = Datastore(on_slot_reclaimed=reclaimed.append)
    ds.pool_set(POOL)
    ds.pod_update_or_add(make_pod())
    slots = {e.slot for e in ds.endpoints()}
    ds.pod_delete("default", "p1")
    assert set(reclaimed) == slots
    assert ds.endpoints() == []


def test_slot_reclaim_callback_runs_outside_lock():
    """ADVICE r1: the reclaim callback may block (scraper join, device
    dispatch); it must fire after the datastore lock is released so
    concurrent readers never stall behind it."""
    held_during_callback = []
    ds = Datastore(
        on_slot_reclaimed=lambda s: held_during_callback.append(
            ds._lock._is_owned()
        )
    )
    ds.pool_set(POOL)
    ds.pod_update_or_add(make_pod())
    ds.pod_delete("default", "p1")
    # Resync-driven evictions (selector change) go through the same path.
    ds.pod_update_or_add(make_pod())
    ds.pool_set(
        POOL.replace(selector={"app": "other"}) if hasattr(POOL, "replace")
        else POOL.__class__(**{**POOL.__dict__, "selector": {"app": "other"}}),
        pod_lister=lambda: [make_pod()],
    )
    ds.clear()
    assert held_during_callback and not any(held_during_callback)


def test_slot_not_reusable_until_reclaim_callback_ran():
    """The callback contract is 'before the slot is reused': an allocation
    racing the (deferred, lock-free) callback must NOT receive the slot, or
    the callback would wipe the new owner's scheduler state."""
    intruder_slots: list[set] = []

    def reclaim(slot: int) -> None:
        # Admit a pod DURING the callback — the freed slots must not be
        # handed out yet.
        ds.pod_update_or_add(make_pod(name="intruder", ip="10.0.0.50"))
        intruder_slots.append(
            {e.slot for e in ds.endpoints() if e.pod_name == "intruder"}
        )

    ds = Datastore(on_slot_reclaimed=reclaim)
    ds.pool_set(POOL)
    ds.pod_update_or_add(make_pod())
    victim_slots = {e.slot for e in ds.endpoints()}
    ds.pod_delete("default", "p1")
    assert intruder_slots and all(
        not (got & victim_slots) for got in intruder_slots
    )


def test_slot_reuse_is_lowest_first_and_stable():
    ds = Datastore()
    ds.pool_set(POOL)
    for i in range(3):
        ds.pod_update_or_add(make_pod(name=f"p{i}", ip=f"10.0.0.{i}"))
    assert {e.slot for e in ds.endpoints()} == set(range(6))
    ds.pod_delete("default", "p0")
    ds.pod_update_or_add(make_pod(name="p9", ip="10.0.0.9"))
    # Freed slots are reused before new ones.
    assert {e.slot for e in ds.endpoints()} == set(range(6))
    # Existing endpoints kept their slots.
    p1_slots = {e.slot for e in ds.endpoints() if e.pod_name == "p1"}
    ds.pod_update_or_add(make_pod(name="p1", ip="10.0.0.42"))
    assert {e.slot for e in ds.endpoints() if e.pod_name == "p1"} == p1_slots


def test_capacity_exhaustion_degrades_gracefully():
    """VERDICT r1 weak #7: slot exhaustion must not crash the reconciler —
    overflowed endpoints are skipped (counted) and admitted once churn
    frees a slot."""
    ds = Datastore(max_slots=2)
    ds.pool_set(POOL)  # two target ports -> 2 slots per pod
    ds.pod_update_or_add(make_pod(name="a", ip="10.0.0.1"))
    assert len(ds.endpoints()) == 2
    # Third/fourth endpoint don't fit; no exception, overflow counted.
    ds.pod_update_or_add(make_pod(name="b", ip="10.0.0.2"))
    assert len(ds.endpoints()) == 2
    assert ds.overflow_count() == 2
    # Churn frees slots; the next reconcile of b admits it.
    ds.pod_delete("default", "a")
    ds.pod_update_or_add(make_pod(name="b", ip="10.0.0.2"))
    assert {e.pod_name for e in ds.endpoints()} == {"b"}


def test_pool_change_triggers_resync():
    """Selector change must evict pods that no longer match (reference
    datastore.go:131-147 podResyncAll)."""
    ds = Datastore()
    pods = [
        make_pod(name="a", labels={"app": "vllm"}),
        make_pod(name="b", ip="10.0.0.2", labels={"app": "other"}),
    ]
    ds.pool_set(POOL, pod_lister=lambda: pods)
    assert {e.pod_name for e in ds.endpoints()} == {"a"}
    new_pool = EndpointPool(
        selector={"app": "other"}, target_ports=[8000, 8002], namespace="default"
    )
    ds.pool_set(new_pool, pod_lister=lambda: pods)
    assert {e.pod_name for e in ds.endpoints()} == {"b"}


def test_target_port_change_resync():
    ds = Datastore()
    pods = [make_pod()]
    ds.pool_set(POOL, pod_lister=lambda: pods)
    assert len(ds.endpoints()) == 2
    ds.pool_set(
        EndpointPool(selector={"app": "vllm"}, target_ports=[8000],
                     namespace="default"),
        pod_lister=lambda: pods,
    )
    assert [e.port for e in ds.endpoints()] == [8000]


def test_clear_frees_everything():
    ds = Datastore()
    ds.pool_set(POOL)
    ds.pod_update_or_add(make_pod())
    ds.clear()
    assert not ds.pool_has_synced()
    assert ds.endpoints() == []


def test_concurrent_writes_no_deadlock():
    """reference datastore_test.go:61,867 concurrency coverage."""
    ds = Datastore()
    ds.pool_set(POOL)
    errs = []

    def writer(i):
        try:
            for j in range(20):
                ds.pod_update_or_add(make_pod(name=f"p{i}", ip=f"10.0.{i}.{j}"))
                ds.endpoints()
                ds.pool_set(POOL, pod_lister=lambda: [])
                ds.pool_set(POOL)
        except Exception as e:  # pragma: no cover
            errs.append(e)

    threads = [threading.Thread(target=writer, args=(i,)) for i in range(8)]
    [t.start() for t in threads]
    [t.join() for t in threads]
    assert not errs


# ---------------------------------------------------------------------------
# Reconcilers over FakeCluster
# ---------------------------------------------------------------------------


def make_api_pool(selector=None, ports=(8000, 8002)) -> api.InferencePool:
    return api.InferencePool(
        metadata=api.ObjectMeta(name="pool", namespace="default"),
        spec=api.InferencePoolSpec(
            selector=api.LabelSelector(matchLabels=selector or {"app": "vllm"}),
            targetPorts=[api.Port(p) for p in ports],
            endpointPickerRef=api.EndpointPickerRef(name="epp", port=api.Port(9002)),
        ),
    )


def setup_wired():
    cluster = FakeCluster()
    ds = Datastore()
    gknn = GKNN(api.GROUP, "InferencePool", "default", "pool")
    wire(
        cluster,
        InferencePoolReconciler(cluster, ds, gknn),
        PodReconciler(cluster, ds),
    )
    return cluster, ds


def test_reconcile_flow_end_to_end():
    cluster, ds = setup_wired()
    cluster.apply_pool(make_api_pool())
    assert ds.pool_has_synced()
    cluster.apply_pod(make_pod())
    assert len(ds.endpoints()) == 2
    # Pod goes unready WHILE serving -> graceful drain, not eviction
    # (docs/RESILIENCE.md; deviation from pod_reconciler.go:90-102):
    # the endpoints stay live for in-flight streams, marked DRAINING.
    cluster.apply_pod(make_pod(ready=False))
    assert [e.draining for e in ds.endpoints()] == [True, True]
    # Readiness flap back -> the drain cancels, full candidacy returns.
    cluster.apply_pod(make_pod())
    assert [e.draining for e in ds.endpoints()] == [False, False]
    assert len(ds.pick_candidates()) == 2
    # The actual deletion event reclaims immediately.
    cluster.delete_pod("default", "p1")
    assert ds.endpoints() == []


def test_pod_before_pool_requeues():
    cluster = FakeCluster()
    ds = Datastore()
    pr = PodReconciler(cluster, ds)
    cluster.apply_pod(make_pod())
    res = pr.reconcile("default", "p1")
    assert isinstance(res, RequeueAfter) and res.seconds == 5.0


def test_pool_delete_clears_datastore():
    cluster, ds = setup_wired()
    cluster.apply_pool(make_api_pool())
    cluster.apply_pod(make_pod())
    cluster.delete_pool("default", "pool")
    assert not ds.pool_has_synced()
    assert ds.endpoints() == []


def test_other_pool_identity_ignored():
    """Scoped cache: only the configured pool name/namespace is consumed
    (reference controller_manager.go:45-68)."""
    cluster, ds = setup_wired()
    other = make_api_pool()
    other.metadata.name = "other-pool"
    cluster.apply_pool(other)
    assert not ds.pool_has_synced()


def test_nonmatching_pod_labels_not_admitted():
    cluster, ds = setup_wired()
    cluster.apply_pool(make_api_pool())
    cluster.apply_pod(make_pod(labels={"app": "nope"}))
    assert ds.endpoints() == []


def test_target_port_renumber_updates_existing_endpoints():
    """targetPorts [8000]->[9000]: same rank, new port — picks must route
    to the new port immediately."""
    ds = Datastore()
    pods = [make_pod()]
    ds.pool_set(
        EndpointPool(selector={"app": "vllm"}, target_ports=[8000],
                     namespace="default"),
        pod_lister=lambda: pods,
    )
    old_slot = ds.endpoints()[0].slot
    ds.pool_set(
        EndpointPool(selector={"app": "vllm"}, target_ports=[9000],
                     namespace="default"),
        pod_lister=lambda: pods,
    )
    eps = ds.endpoints()
    assert [e.port for e in eps] == [9000]
    assert eps[0].slot == old_slot  # rank identity (and slot) preserved


def test_hostport_index_tracks_lifecycle():
    """endpoint_by_hostport must stay consistent through add/refresh/
    renumber/delete (it indexes the served-feedback hot path)."""
    ds = Datastore()
    ds.pool_set(POOL)
    ds.pod_update_or_add(make_pod())
    assert ds.endpoint_by_hostport("10.0.0.1:8000").pod_name == "p1"
    # IP change re-keys the index.
    ds.pod_update_or_add(make_pod(ip="10.0.0.9"))
    assert ds.endpoint_by_hostport("10.0.0.1:8000") is None
    assert ds.endpoint_by_hostport("10.0.0.9:8000").pod_name == "p1"
    # Port renumber re-keys it too.
    ds.pool_set(
        EndpointPool(selector={"app": "vllm"}, target_ports=[9000, 8002],
                     namespace="default"),
        pod_lister=lambda: [make_pod(ip="10.0.0.9")],
    )
    assert ds.endpoint_by_hostport("10.0.0.9:8000") is None
    assert ds.endpoint_by_hostport("10.0.0.9:9000") is not None
    ds.pod_delete("default", "p1")
    assert ds.endpoint_by_hostport("10.0.0.9:9000") is None


def test_hostport_collision_does_not_unindex_other_endpoint():
    """k8s IP reuse: pod B takes A's old IP while A's stale endpoint still
    exists; refreshing A must not evict B's index entry."""
    ds = Datastore()
    ds.pool_set(
        EndpointPool(selector={"app": "vllm"}, target_ports=[8000],
                     namespace="default")
    )
    ds.pod_update_or_add(make_pod(name="a", ip="10.0.0.5"))
    # B is created with A's hostport (A not yet updated/deleted).
    ds.pod_update_or_add(make_pod(name="b", ip="10.0.0.5"))
    # A refreshes away to a new IP — B must stay indexed at the shared key.
    ds.pod_update_or_add(make_pod(name="a", ip="10.0.0.6"))
    assert ds.endpoint_by_hostport("10.0.0.5:8000").pod_name == "b"
    assert ds.endpoint_by_hostport("10.0.0.6:8000").pod_name == "a"
    # Deleting A later must not remove B's entry either.
    ds.pod_delete("default", "a")
    assert ds.endpoint_by_hostport("10.0.0.5:8000").pod_name == "b"


def test_resync_at_capacity_admits_after_evictions():
    """A selector change at full capacity must hand the freed slots to the
    newly matching pods in the SAME resync (evict -> drain reclaims ->
    admit) — a stable pod emits no later event to retry."""
    reclaimed = []
    ds = Datastore(max_slots=2, on_slot_reclaimed=reclaimed.append)
    ds.pool_set(POOL)
    ds.pod_update_or_add(make_pod(name="a", ip="10.0.0.1"))  # both slots
    pods = [
        make_pod(name="a", ip="10.0.0.1"),
        make_pod(name="b", ip="10.0.0.2", labels={"app": "other"}),
    ]
    new_pool = POOL.__class__(
        selector={"app": "other"},
        target_ports=list(POOL.target_ports),
        namespace=POOL.namespace,
    )
    ds.pool_set(new_pool, pod_lister=lambda: pods)
    assert {e.pod_name for e in ds.endpoints()} == {"b"}
    assert len(ds.endpoints()) == 2
    assert ds.overflow_count() == 0
