"""Vectorized wave assembly (ISSUE 1): the collector's hot host path.

Two contracts: (1) the numpy column assembly produces bit-identical wave
tensors to the old per-request Python loop (kept below as the parity
oracle); (2) assembling the north-star 1024-request wave stays within a
LOOSE CPU wall-clock budget — a regression back to per-request Python
looping (~N x M interpreted operations) blows straight through it.
"""

import time
from types import SimpleNamespace

import numpy as np

from gie_tpu.extproc.server import PickRequest
from gie_tpu.sched import constants as C
from gie_tpu.sched.batching import _Pending, assemble_wave
from gie_tpu.sched.hashing import batch_chunk_hashes
from gie_tpu.sched.types import chunk_bucket_for
from gie_tpu.utils.lora import LoraRegistry


def _items(n: int, m: int) -> list:
    cands = [SimpleNamespace(slot=j) for j in range(m)]
    base = b"SYSTEM: shared prefix for tier %d. "
    return [
        _Pending(
            PickRequest(
                headers={},
                body=(base % (i % 16)) * 4 + b"user %d" % i,
                model=("adapter-%d" % (i % 12)) if i % 3 else "",
                decode_tokens=float(i % 200),
            ),
            cands,
        )
        for i in range(n)
    ]


def _reference_assembly(batch, mb, registry):
    """The pre-ISSUE-1 per-request loop, verbatim: the parity oracle."""
    n = len(batch)
    prompts = [it.req.body or b"" for it in batch]
    hashes, counts = batch_chunk_hashes(prompts)
    cb = chunk_bucket_for(int(counts.max()) if n else 1)
    hashes = hashes[:, :cb]
    lora = np.full((n,), -1, np.int32)
    crit = np.full((n,), C.Criticality.STANDARD, np.int32)
    plen = np.zeros((n,), np.float32)
    dlen = np.zeros((n,), np.float32)
    mask = np.zeros((n, mb), bool)
    for i, it in enumerate(batch):
        lora[i] = registry.id_for(it.req.model)
        crit[i] = it.band
        plen[i] = float(len(prompts[i]))
        dlen[i] = C.CHARS_PER_TOKEN * float(it.req.decode_tokens or 0.0)
        for ep in it.candidates:
            if 0 <= ep.slot < mb:
                mask[i, ep.slot] = True
    return lora, crit, plen, dlen, hashes, counts, mask


def test_vectorized_assembly_matches_reference_loop():
    items = _items(96, 48)
    reqs, plen, dlen, lora = assemble_wave(items, 48, LoraRegistry())
    r_lora, r_crit, r_plen, r_dlen, r_hashes, r_counts, r_mask = (
        _reference_assembly(items, 48, LoraRegistry()))
    np.testing.assert_array_equal(lora, r_lora)
    np.testing.assert_array_equal(plen, r_plen)
    np.testing.assert_array_equal(dlen, r_dlen)
    np.testing.assert_array_equal(np.asarray(reqs.lora_id), r_lora)
    np.testing.assert_array_equal(np.asarray(reqs.criticality), r_crit)
    np.testing.assert_array_equal(np.asarray(reqs.prompt_len), r_plen)
    np.testing.assert_array_equal(np.asarray(reqs.decode_len), r_dlen)
    np.testing.assert_array_equal(np.asarray(reqs.chunk_hashes), r_hashes)
    np.testing.assert_array_equal(np.asarray(reqs.n_chunks), r_counts)
    np.testing.assert_array_equal(np.asarray(reqs.subset_mask), r_mask)
    assert bool(np.asarray(reqs.valid).all())


def test_assembly_respects_subset_hints_and_out_of_range_slots():
    """Strict-subset hints survive vectorization: candidate slots outside
    the wave's M bucket are dropped, in-range ones land exactly."""
    items = [
        _Pending(PickRequest(headers={}, body=b"x"),
                 [SimpleNamespace(slot=s) for s in slots])
        for slots in ([0, 3], [7, 400], [5], [])
    ]
    reqs, _, _, _ = assemble_wave(items, 8, LoraRegistry())
    mask = np.asarray(reqs.subset_mask)
    expect = np.zeros((4, 8), bool)
    expect[0, [0, 3]] = True
    expect[1, 7] = True   # 400 is beyond the bucket -> dropped
    expect[2, 5] = True
    np.testing.assert_array_equal(mask, expect)


def test_assembly_1024_wave_within_budget():
    """Guard: the north-star wave (1024 requests x 256 candidate slots)
    assembles via numpy column ops within a loose CPU budget."""
    items = _items(1024, 256)
    reg = LoraRegistry()
    assemble_wave(items[:8], 256, reg)  # warm numpy/jax dispatch paths
    t0 = time.perf_counter()
    reqs, plen, dlen, lora = assemble_wave(items, 256, reg)
    dt = time.perf_counter() - t0
    assert int(np.asarray(reqs.valid).shape[0]) == 1024
    assert int(np.asarray(reqs.subset_mask).sum()) == 1024 * 256
    assert dt < 0.25, f"1024-wave assembly took {dt * 1e3:.1f}ms (budget 250ms)"
