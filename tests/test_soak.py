"""Whole-stack soak: sustained live traffic + churn over real transports.

Round-5 robustness evidence tying every runtime seam together AT ONCE —
the individual paths are each tested elsewhere; this exercises them
concurrently for several seconds the way production would:

  fake apiserver (tests/fakeapi.py, chunked watch)
    -> KubeClusterClient watch loop -> reconcilers -> datastore
  stub model servers on DISTINCT loopback IPs (127.0.0.x) serving real
  /metrics HTTP -> the runner's per-endpoint fast-poll Scraper -> dense
  MetricsStore
  concurrent Envoy-shaped ext-proc sessions (raw wire bytes over a real
  gRPC socket) -> StreamingServer -> BatchingTPUPicker -> jitted cycle
  churn thread: pod deletes / re-adds / readiness flips via the apiserver

Asserts: the server answers throughout, every pick names an endpoint
that was live at (or within the eventual-consistency window of) pick
time, deleted pods stop being picked, real scrapes land in the dense
store, and the stack is consistent at quiescence.

Reference analogues: conformance gateway_following_epp_routing soak
(conformance/tests/gateway_following_epp_routing.go:167-169: 100
requests, 10 concurrent, 0 misroutes) and the implementers' guide
lifecycle (site-src/guides/implementers.md:125-158).
"""

import http.server
import threading
import time
from concurrent import futures

import grpc
import pytest

from gie_tpu.controller.kube import KubeClusterClient
from gie_tpu.extproc import pb
from gie_tpu.extproc import metadata as mdkeys
from gie_tpu.extproc.service import SERVICE_NAME
from gie_tpu.runtime.options import Options
from gie_tpu.runtime.runner import ExtProcServerRunner
from gie_tpu.simulator import StubConfig, VLLMStub

from tests.fakeapi import FakeKubeApiServer
from tests.test_kube_apiserver import NS, POOL, pod_manifest, pool_manifest

_identity = lambda b: b  # noqa: E731


class _StubMetricsServer:
    """Real HTTP /metrics endpoint for one emulated pod, bound to its own
    loopback IP (127.0.0.x all route locally on Linux) so every pod keeps
    the pool's shared targetPort like a real fleet."""

    def __init__(self, ip: str, port: int, stub: VLLMStub):
        handler_stub = stub

        class H(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (stdlib naming)
                body = handler_stub.metrics_text().encode()
                self.send_response(200)
                self.send_header("Content-Type", "text/plain")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # silence
                pass

        self.httpd = http.server.ThreadingHTTPServer((ip, port), H)
        self.thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True)
        self.thread.start()

    def close(self):
        self.httpd.shutdown()
        self.httpd.server_close()


def _session_frames(i: int) -> list[bytes]:
    from tests.test_extproc_wire import (
        header_map_bytes,
        header_value_bytes,
        http_headers_bytes,
        ld,
    )

    hmap = header_map_bytes(
        header_value_bytes(":method", raw=b"POST"),
        header_value_bytes(":path", raw=b"/v1/completions"),
        header_value_bytes("content-type", raw=b"application/json"),
    )
    frame = ld(2, http_headers_bytes(hmap, end_of_stream=False))
    body = (b'{"model":"demo","prompt":"SYSTEM: shared prefix | user %d",'
            b'"max_tokens":16}' % (i % 7))
    inner = ld(1, body) + b"\x10\x01"  # end_of_stream=true
    return [frame, ld(3, inner)]


def _dest_of(raws) -> str:
    """Primary destination: the header carries the ORDERED fallback list
    (004 README:50-82, comma-separated); the first entry is the pick."""
    hdr = pb.ProcessingResponse.FromString(raws[0])
    muts = {
        h.header.key: (h.header.raw_value or h.header.value.encode())
        for h in hdr.request_headers.response.header_mutation.set_headers
    }
    v = muts.get(mdkeys.DESTINATION_ENDPOINT_KEY, b"")
    return v.decode().split(",")[0]


@pytest.mark.slow
def test_whole_stack_soak_with_churn():
    # slow-marked: the >100-sessions floor is a THROUGHPUT assertion, and
    # this container's CPU is bistable under load (16/s vs 4/s across
    # otherwise-identical runs) — a hard rate gate cannot run in tier-1
    # without flaking. Run explicitly: pytest -m slow tests/test_soak.py
    srv = FakeKubeApiServer()
    stubs: dict[str, VLLMStub] = {}
    metric_servers = []
    n_pods = 5
    port = 18080
    ips = [f"127.0.0.{i + 2}" for i in range(n_pods)]
    for i, ip in enumerate(ips):
        stub = VLLMStub(StubConfig(), name=f"pod-{i}")
        stubs[f"{ip}:{port}"] = stub
        metric_servers.append(_StubMetricsServer(ip, port, stub))

    srv.apply("pools", pool_manifest(ports=(port,)))
    for i, ip in enumerate(ips):
        srv.apply("pods", pod_manifest(f"pod-{i}", ip))

    client = KubeClusterClient(
        NS, POOL, server=srv.url, token="t",
        watch_timeout_s=1, backoff_s=0.05)
    opts = Options(
        pool_name=POOL, pool_namespace=NS, secure_serving=False,
        grpc_port=0, grpc_health_port=0, metrics_port=0,
        scrape_interval_ms=50.0,
    )
    runner = ExtProcServerRunner(opts, client)
    runner.setup()
    grpc_port = runner.start()
    client.start()
    channel = None
    stop = threading.Event()
    errors: list = []
    picked_log: list[tuple[float, str]] = []
    # (hostport, deleted_at, readded_at) intervals, appended once each
    # interval is CLOSED so the checker never races a half-open window.
    dead_windows: list[tuple[str, float, float]] = []

    try:
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if len(runner.datastore.endpoints()) == n_pods:
                break
            time.sleep(0.05)
        assert len(runner.datastore.endpoints()) == n_pods

        channel = grpc.insecure_channel(f"127.0.0.1:{grpc_port}")
        raw = channel.stream_stream(
            f"/{SERVICE_NAME}/Process",
            request_serializer=_identity,
            response_deserializer=_identity,
        )

        # Warm the live wave shapes AND the churn paths through the real
        # stack BEFORE the measured window: the soak asserts sustained
        # steady-state throughput, and on a cold CPU backend the
        # first-use jit compiles (the cycle, then the evict/clear
        # helpers the first pod delete triggers — several seconds each
        # here) would otherwise consume the whole window. Cold-compile
        # behavior has its own coverage (warm_lattice / pipeline tests).
        for i in range(3):
            list(raw(iter(_session_frames(900_000 + i)), timeout=120))
        srv.delete("pods", NS, "pod-3")
        time.sleep(0.5)
        srv.apply("pods", pod_manifest("pod-3", ips[3]))
        time.sleep(0.5)
        list(raw(iter(_session_frames(900_010)), timeout=120))

        def requester(seed: int) -> None:
            i = seed * 1000
            try:
                while not stop.is_set():
                    i += 1
                    out = list(raw(iter(_session_frames(i)), timeout=30))
                    dest = _dest_of(out)
                    if dest:
                        picked_log.append((time.monotonic(), dest))
            except Exception as e:  # pragma: no cover
                errors.append(e)

        def churner() -> None:
            try:
                hostport = f"{ips[3]}:{port}"
                while not stop.is_set():
                    # Delete pod-3, leave it dead for LONGER than the
                    # misroute grace so the assertion below has a live
                    # window to check, then re-add.
                    t_del = time.monotonic()
                    srv.delete("pods", NS, "pod-3")
                    time.sleep(1.2)
                    srv.apply("pods", pod_manifest("pod-3", ips[3]))
                    # Interval recorded AFTER the re-add so the main
                    # thread never sees a half-open window.
                    dead_windows.append((hostport, t_del, time.monotonic()))
                    time.sleep(0.5)
                    # Readiness flip on pod-4.
                    srv.apply("pods", pod_manifest(
                        "pod-4", ips[4], ready=False))
                    time.sleep(0.5)
                    srv.apply("pods", pod_manifest("pod-4", ips[4]))
                    time.sleep(0.5)
            except Exception as e:  # pragma: no cover
                errors.append(e)

        threads = [threading.Thread(target=requester, args=(s,))
                   for s in range(3)]
        threads.append(threading.Thread(target=churner))
        [t.start() for t in threads]
        # 12 s window: at this container's churn-steady-state rate
        # (~15 sessions/s across the three requesters) the >100-session
        # floor keeps ~1.8x headroom against CPU contention spikes.
        time.sleep(12.0)
        stop.set()
        [t.join(timeout=20) for t in threads]
        assert not errors, errors[:3]

        # Sustained service: hundreds of successful routed sessions.
        assert len(picked_log) > 100, len(picked_log)
        all_hostports = {f"{ip}:{port}" for ip in ips}
        assert {d for _, d in picked_log} <= all_hostports

        # Misroute bound: a deleted pod may absorb picks only within the
        # watch->datastore eventual-consistency window after the delete
        # (0.4 s grace << the 1.2 s dead window, so every interval has
        # ~0.8 s of genuinely-checked dead time — the conformance soak
        # tolerates 0 misroutes only AFTER sync). The churner must have
        # produced at least one closed window or this checks nothing.
        assert dead_windows, "churner produced no delete/re-add interval"
        for host, t_del, t_readd in dead_windows:
            for t_pick, dest in picked_log:
                if dest == host and t_del + 0.4 < t_pick < t_readd:
                    raise AssertionError(
                        f"{host} picked {t_pick - t_del:.2f}s after "
                        "deletion (grace 0.4s)")

        # The REAL scrape path landed data for live endpoints: the dense
        # store has rows for every live slot (fetched over HTTP from the
        # per-pod loopback servers).
        live = runner.datastore.endpoints()
        assert len(live) == n_pods  # churner re-adds before stopping
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            if all(runner.metrics_store._has_data[ep.slot] for ep in live):
                break
            time.sleep(0.05)
        missing = [ep.hostport for ep in live
                   if not runner.metrics_store._has_data[ep.slot]]
        assert not missing, f"no scrape data for {missing}"

        # Quiescent consistency: a fresh session still routes correctly.
        out = list(raw(iter(_session_frames(999_999)), timeout=30))
        assert _dest_of(out) in {ep.hostport for ep in live}
    finally:
        stop.set()
        if channel is not None:
            channel.close()
        client.stop()
        runner.stop(grace=1.0)
        for ms in metric_servers:
            ms.close()
        srv.close()
