"""gie-fair unit suite (ISSUE 11, docs/FAIRNESS.md): weighted-DRR
ordering invariants (seeded property fuzz), budget ledgers + the
over-fair-share verdict, the bounded-cardinality tenant labeler, and
the picker's preemptive per-tenant shed.

The DRR invariants pinned here are the flow queue's contract:

  * the output is a permutation of the input;
  * criticality bands drain strictly CRITICAL -> STANDARD -> SHEDDABLE;
  * per-tenant FIFO is preserved within a band;
  * long-run drained-cost shares converge to the configured weight
    ratios while tenants stay backlogged;
  * empty / single-item / single-tenant inputs degenerate to FIFO.
"""

from __future__ import annotations

import numpy as np
import pytest

from gie_tpu.fairness import FairnessState, parse_weights
from gie_tpu.fairness.budgets import TenantBudgets, WindowedSum
from gie_tpu.fairness.drr import DeficitRoundRobin, FairnessConfig


class Item:
    __slots__ = ("band", "tenant", "cost", "seq")

    def __init__(self, band, tenant, cost=1.0, seq=0):
        self.band = band
        self.tenant = tenant
        self.cost = cost
        self.seq = seq

    def __repr__(self):
        return f"Item(b{self.band},{self.tenant},c{self.cost},#{self.seq})"


class Clock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


# ==========================================================================
# DRR ordering invariants
# ==========================================================================


def _check_invariants(items, out):
    assert sorted(map(id, out)) == sorted(map(id, items)), "not a permutation"
    bands = [it.band for it in out]
    assert bands == sorted(bands), "band ordering not strict"
    seen: dict[tuple, list] = {}
    for it in out:
        seen.setdefault((it.band, it.tenant), []).append(it.seq)
    for key, seqs in seen.items():
        assert seqs == sorted(seqs), f"per-tenant FIFO broken for {key}"


def test_fuzz_invariants_random_mixes():
    rng = np.random.default_rng(20260804)
    for trial in range(25):
        n_tenants = int(rng.integers(1, 7))
        weights = {f"w{t}": float(rng.uniform(0.5, 4.0))
                   for t in range(n_tenants) if rng.random() < 0.5}
        drr = DeficitRoundRobin(FairnessConfig(weights=weights))
        counters: dict[tuple, int] = {}
        for wave in range(3):  # persistent state across waves
            items = []
            for _ in range(int(rng.integers(0, 60))):
                band = int(rng.integers(1, 4))
                tenant = f"w{int(rng.integers(n_tenants))}"
                key = (band, tenant)
                counters[key] = counters.get(key, 0) + 1
                items.append(Item(band, tenant,
                                  cost=float(rng.uniform(0.25, 8.0)),
                                  seq=counters[key]))
            take = int(rng.integers(0, len(items) + 2)) if items else 0
            out = drr.order(items, take=take)
            _check_invariants(items, out)


def test_degenerate_cases():
    drr = DeficitRoundRobin()
    assert drr.order([]) == []
    one = Item(2, "a", 1.0, 0)
    assert drr.order([one]) == [one]
    # Single tenant: plain FIFO regardless of costs.
    items = [Item(2, "a", float(c), i) for i, c in enumerate([8, 1, 4, 2])]
    assert drr.order(items) == items
    assert drr.deficits() == {}


def test_weighted_share_convergence_over_waves():
    """Two permanently-backlogged equal-cost tenants at weights 3:1
    drain ~3:1 over many waves (the persistent-deficit carry)."""
    drr = DeficitRoundRobin(FairnessConfig(weights={"a": 3.0, "b": 1.0}))
    pending: list = []
    seqs = {"a": 0, "b": 0}
    drained = {"a": 0, "b": 0}
    for wave in range(50):
        for t in ("a", "b"):
            while sum(1 for it in pending if it.tenant == t) < 16:
                seqs[t] += 1
                pending.append(Item(2, t, 1.0, seqs[t]))
        pending = drr.order(pending, take=8)
        batch, pending = pending[:8], pending[8:]
        for it in batch:
            drained[it.tenant] += 1
    ratio = drained["a"] / max(drained["b"], 1)
    assert 2.4 < ratio < 3.6, drained


def test_cost_weighted_shares_equal_cost_not_equal_count():
    """Uniform weights + 4x cost asymmetry: drained COST equalizes, so
    the big-request tenant gets ~1/4 the SLOTS — the exact hole the
    count-RR seed had."""
    drr = DeficitRoundRobin()
    pending: list = []
    seqs = {"big": 0, "small": 0}
    cost_drained = {"big": 0.0, "small": 0.0}
    for wave in range(40):
        for t, c in (("big", 4.0), ("small", 1.0)):
            while sum(1 for it in pending if it.tenant == t) < 24:
                seqs[t] += 1
                pending.append(Item(2, t, c, seqs[t]))
        pending = drr.order(pending, take=10)
        batch, pending = pending[:10], pending[10:]
        for it in batch:
            cost_drained[it.tenant] += it.cost
    ratio = cost_drained["big"] / cost_drained["small"]
    assert 0.7 < ratio < 1.4, cost_drained


def test_bands_drain_strictly_before_fairness():
    drr = DeficitRoundRobin()
    items = ([Item(3, "flood", 1.0, i) for i in range(8)]
             + [Item(2, "std", 1.0, i) for i in range(2)]
             + [Item(1, "crit", 1.0, 0)])
    out = drr.order(items)
    assert out[0].tenant == "crit"
    assert [it.band for it in out[:3]] == [1, 2, 2]


def test_deficit_state_bounded_and_reported():
    drr = DeficitRoundRobin(FairnessConfig(max_tracked=4))
    for wave in range(10):
        items = [Item(2, f"t{wave}-{k}", 1.0, i)
                 for k in range(3) for i in range(4)]
        drr.order(items, take=3)
    assert len(drr._deficit) <= 4 + 3  # cap + one wave's live tenants
    for key, val in drr.deficits().items():
        assert ":" in key and val >= 0.0


# ==========================================================================
# Budgets: windows, over-share verdict, labeler
# ==========================================================================


def test_windowed_sum_ages_out():
    clock = Clock()
    ws = WindowedSum(8.0)
    ws.note(5.0, clock.t)
    assert ws.total(clock.t) == 5.0
    assert ws.total(clock.t + 4.0) == 5.0
    assert ws.total(clock.t + 20.0) == 0.0


def _budgets(clock, **cfg_kw):
    cfg = dict(window_s=8.0, eval_interval_s=0.0001, top_k=2)
    cfg.update(cfg_kw)
    return TenantBudgets(FairnessConfig(**cfg), clock=clock)


def test_over_share_flags_flooder_not_balanced_pair():
    clock = Clock()
    b = _budgets(clock)
    for _ in range(90):
        b.note_arrival("hog", 1.0)
    for _ in range(10):
        b.note_arrival("quiet", 1.0)
    clock.t += 0.01
    over = b.over_share_set()
    assert "hog" in over and "quiet" not in over
    # Balanced pair: nobody over (factor 2 x fair share 0.5 = 1.0).
    b2 = _budgets(clock)
    for _ in range(50):
        b2.note_arrival("a", 1.0)
        b2.note_arrival("b", 1.0)
    clock.t += 0.01
    assert b2.over_share_set() == frozenset()


def test_over_share_never_flags_a_lone_tenant():
    clock = Clock()
    b = _budgets(clock)
    for _ in range(200):
        b.note_arrival("only", 4.0)
    clock.t += 0.01
    assert b.over_share_set() == frozenset()


def test_over_share_respects_weights():
    clock = Clock()
    b = _budgets(clock, weights={"paid": 8.0})
    # "paid" offers 6x the neighbor — but its weight entitles it to 8/9.
    for _ in range(60):
        b.note_arrival("paid", 1.0)
    for _ in range(10):
        b.note_arrival("small", 1.0)
    clock.t += 0.01
    assert "paid" not in b.over_share_set()


def test_over_share_ages_out_with_the_window():
    clock = Clock()
    b = _budgets(clock)
    for _ in range(90):
        b.note_arrival("hog", 1.0)
    b.note_arrival("quiet", 1.0)
    clock.t += 0.01
    assert "hog" in b.over_share_set()
    clock.t += 30.0  # the flood ages out entirely
    b.note_arrival("quiet", 1.0)
    assert b.over_share_set() == frozenset()


def test_labeler_top_k_other_and_default():
    clock = Clock()
    b = _budgets(clock, top_k=2)
    for _ in range(300):
        b.note_arrival("big1", 1.0)
    for _ in range(200):
        b.note_arrival("big2", 1.0)
    for i in range(40):
        b.note_arrival(f"tail{i}", 1.0)
    assert b.label("big1") == "big1"
    assert b.label("big2") == "big2"
    assert b.label("tail3") == "other"
    assert b.label("never-seen") == "other"
    assert b.label("") == "default"


def test_labeler_cardinality_hard_cap():
    """Adversarial tenant churn cannot mint unbounded label values: at
    most label_cap (4 x top_k) distinct tenants are ever promoted."""
    clock = Clock()
    b = _budgets(clock, top_k=2, max_tracked=16)
    promoted = set()
    for round_ in range(60):
        t = f"churn{round_}"
        for _ in range(300):  # each churn tenant becomes top-traffic
            b.note_arrival(t, 1.0)
        label = b.label(t)
        if label not in ("other", "default"):
            promoted.add(label)
        clock.t += 10.0  # previous rounds age out of the window
    assert len(promoted) <= 8  # label_cap = 4 * top_k


def test_report_shape():
    clock = Clock()
    b = _budgets(clock)
    b.note_arrival("a", 2.0)
    b.note_drained("a", 2.0)
    b.note_shed("a")
    b.note_serve("a", ok=False)
    rep = b.report()
    row = rep["tenants"]["a"]
    assert row["requests_total"] == 1
    assert row["arrival_cost_w"] == 2.0
    assert row["drained_cost_w"] == 2.0
    assert row["shed_samples_w"] == 2  # 1 arrival + 1 shed
    # A fully-shed tenant reads 1.0, not 0.5: the shed request notes
    # BOTH an arrival and a shed, and the rate is sheds/ARRIVALS.
    assert row["shed_rate_w"] == 1.0
    assert row["serve_error_rate_w"] == 1.0
    assert rep["window_s"] == 8.0
    # Half-shed tenant: 4 arrivals, 2 sheds -> 0.5.
    b2 = _budgets(clock)
    for _ in range(4):
        b2.note_arrival("h", 1.0)
    b2.note_shed("h")
    b2.note_shed("h")
    assert b2.report()["tenants"]["h"]["shed_rate_w"] == 0.5


def test_parse_weights():
    assert parse_weights(["a=2", "b=0.5,c=1.5"]) == {
        "a": 2.0, "b": 0.5, "c": 1.5}
    assert parse_weights([]) == {}
    with pytest.raises(ValueError, match="TENANT=WEIGHT"):
        parse_weights(["nope"])
    with pytest.raises(ValueError, match="not a number"):
        parse_weights(["a=fast"])
    with pytest.raises(ValueError, match="> 0"):
        parse_weights(["a=0"])


# ==========================================================================
# Picker integration: preemptive shed + tenants_report
# ==========================================================================


def _picker_stack(**picker_kw):
    from gie_tpu.datastore import Datastore
    from gie_tpu.datastore.objects import EndpointPool, Pod
    from gie_tpu.metricsio import MetricsStore
    from gie_tpu.sched import ProfileConfig, Scheduler
    from gie_tpu.sched.batching import BatchingTPUPicker

    sched = Scheduler(ProfileConfig(load_decay=1.0, queue_limit=4.0))
    ms = MetricsStore()
    ds = Datastore(on_slot_reclaimed=lambda s: (sched.evict_endpoint(s),
                                                ms.remove(s)))
    ds.pool_set(EndpointPool({"app": "x"}, [8000], "default"))
    for i in range(2):
        ds.pod_update_or_add(
            Pod(name=f"p{i}", labels={"app": "x"}, ip=f"10.9.0.{i + 1}"))
    picker = BatchingTPUPicker(sched, ds, ms, **picker_kw)
    return sched, ds, ms, picker


def _pending(band_name, tenant, body=b"x" * 256):
    from gie_tpu.extproc import metadata as mdkeys
    from gie_tpu.extproc.server import PickRequest
    from gie_tpu.sched.batching import _Pending

    headers = {mdkeys.OBJECTIVE_KEY: [band_name]}
    if tenant:
        headers[mdkeys.FLOW_FAIRNESS_ID_KEY] = [tenant]
    return _Pending(PickRequest(headers=headers, body=body),
                    candidates=[type("E", (), {"slot": 0})()])


def test_preemptive_shed_targets_over_share_sheddable_only():
    from gie_tpu.extproc.server import ShedError
    from gie_tpu.sched import constants as C

    sched, ds, ms, picker = _picker_stack()
    try:
        # Saturate every slot in the fairness path's view.
        picker.metrics_store.host_queue_depths = (
            lambda: np.full(C.M_MAX, 100.0))
        # "hog" floods the offered-cost ledger; "quiet" trickles.
        for _ in range(90):
            picker.fairness.note_arrival("hog", 1.0)
        picker.fairness.note_arrival("quiet", 1.0)
        over = picker.fairness.over_share_set()
        assert "hog" in over
        batch = [
            _pending("sheddable", "hog"),
            _pending("sheddable", "quiet"),
            _pending("standard", "hog"),
            _pending("critical", "hog"),
        ]
        kept = picker._preemptive_shed(batch, over)
        # Only the over-share tenant's SHEDDABLE item was shed.
        assert kept == batch[1:]
        err = batch[0].error
        assert isinstance(err, ShedError)
        assert err.tenant == "hog"
        assert batch[0].event.is_set()
    finally:
        picker.close()


def test_preemptive_shed_spares_everyone_without_saturation():
    from gie_tpu.sched import constants as C

    sched, ds, ms, picker = _picker_stack()
    try:
        picker.metrics_store.host_queue_depths = (
            lambda: np.zeros(C.M_MAX))  # free capacity everywhere
        for _ in range(90):
            picker.fairness.note_arrival("hog", 1.0)
        picker.fairness.note_arrival("quiet", 1.0)
        over = picker.fairness.over_share_set()
        batch = [_pending("sheddable", "hog")]
        assert picker._preemptive_shed(batch, over) == batch
        assert batch[0].error is None
    finally:
        picker.close()


def test_tenants_report_explains_queue_and_budgets():
    sched, ds, ms, picker = _picker_stack()
    try:
        picker.fairness.note_arrival("a", 1.0)
        picker.fairness.note_shed("a", "sheddable")
        with picker._cond:
            picker._pending.append(_pending("standard", "a"))
        rep = picker.tenants_report()
        assert rep["queue"] == {"a": {"standard": 1}}
        assert rep["queue_depth"] == 1
        assert "a" in rep["tenants"]
        assert rep["tenants"]["a"]["requests_total"] == 1
        assert "deficits" in rep and "weights" in rep
        with picker._cond:
            picker._pending.clear()
    finally:
        picker.close()


def test_fairness_state_metrics_use_bounded_labels():
    """gie_tenant_* series go through the labeler: a long-tail tenant's
    series lands on 'other', the empty ID on 'default'."""
    from gie_tpu.runtime import metrics as own_metrics

    state = FairnessState(FairnessConfig(top_k=1))
    for _ in range(50):
        state.note_arrival("whale", 1.0)
    state.note_arrival("minnow", 1.0)
    state.note_arrival("", 1.0)
    reg = own_metrics.REGISTRY
    assert reg.get_sample_value(
        "gie_tenant_requests_total", {"tenant": "whale"}) >= 50
    assert reg.get_sample_value(
        "gie_tenant_requests_total", {"tenant": "other"}) >= 1
    assert reg.get_sample_value(
        "gie_tenant_requests_total", {"tenant": "default"}) >= 1
    assert reg.get_sample_value(
        "gie_tenant_requests_total", {"tenant": "minnow"}) is None
