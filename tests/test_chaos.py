"""gie-chaos scenario suite (ISSUE 7, docs/RESILIENCE.md).

Seeded, deterministic fault schedules driven through the REAL stack —
scrape engine, circuit breakers, batching picker, degradation ladder,
replication follower, autoscale actuator — asserting the acceptance
criteria: under correlated endpoint failure (>=25% of the pool), a
metrics blackout, a replication partition, and a kube-API outage, the
EPP serves continuously (no crash, no unbounded error rate),
``gie_degraded_mode`` transitions down AND back up the ladder, and
identical seeds reproduce identical fault schedules bit-for-bit.

Fast scenarios run in the tier-1 gate; the longer mixed-fault soak is
``slow``-marked (``make chaos-smoke`` runs both).
"""

from __future__ import annotations

import threading
import time

import numpy as np
import pytest

from gie_tpu.datastore import Datastore
from gie_tpu.datastore.objects import EndpointPool, Pod
from gie_tpu.extproc.server import PickRequest
from gie_tpu.metricsio import MetricsStore
from gie_tpu.metricsio.engine import ScrapeEngine
from gie_tpu.metricsio.mappings import VLLM
from gie_tpu.resilience import faults
from gie_tpu.resilience.breaker import BreakerBoard, BreakerConfig, BreakerState
from gie_tpu.resilience.faults import FaultInjector, FaultRule
from gie_tpu.resilience.ladder import (
    DegradationLadder, LadderConfig, ResilienceState, Rung)
from gie_tpu.runtime import metrics as own_metrics
from gie_tpu.sched import ProfileConfig, Scheduler
from gie_tpu.sched.batching import BatchingTPUPicker

from tests.test_metricsio_sim import VLLM_TEXT


@pytest.fixture(autouse=True)
def _no_leaked_injector():
    faults.uninstall()
    yield
    faults.uninstall()


@pytest.fixture(autouse=True)
def _flight_recorder():
    """Chaos runs carry a flight recorder (gie-obs): on failure the
    conftest hook dumps the decision records to /tmp/gie-obs so the
    failed scenario explains itself."""
    from gie_tpu import obs
    from gie_tpu.obs.recorder import FlightRecorder

    obs.install(recorder=FlightRecorder(2048))
    yield
    obs.uninstall()


def _fast_ladder(**kw):
    cfg = dict(dispatch_error_streak=2, blackout_stale_s=0.35,
               latency_breach_s=5.0, latency_breach_streak=50,
               recover_streak=2, min_dwell_s=0.05, probe_interval_s=0.01,
               blackout_recover_fraction=0.5)
    cfg.update(kw)
    return DegradationLadder(LadderConfig(**cfg))


def _cluster(n_pods, rs):
    sched = Scheduler(ProfileConfig(load_decay=1.0))
    ms = MetricsStore()
    ds = Datastore(on_slot_reclaimed=lambda s: (sched.evict_endpoint(s),
                                                ms.remove(s)))
    ds.pool_set(EndpointPool({"app": "x"}, [8000], "default"))
    for i in range(n_pods):
        ds.pod_update_or_add(
            Pod(name=f"p{i}", labels={"app": "x"}, ip=f"10.9.1.{i + 1}"))
    picker = BatchingTPUPicker(sched, ds, ms, max_wait_s=0.01,
                               resilience=rs)
    return sched, ds, ms, picker


def _degraded_gauge() -> float:
    v = own_metrics.REGISTRY.get_sample_value("gie_degraded_mode")
    return -1.0 if v is None else v


# --------------------------------------------------------------------------
# Scenario: correlated endpoint death (2 of 8 = 25% of the pool)
# --------------------------------------------------------------------------


def test_correlated_endpoint_death_quarantines_and_recovers():
    rs = ResilienceState(
        board=BreakerBoard(BreakerConfig(open_after=3, open_s=1.0,
                                         close_after=2)),
        ladder=_fast_ladder())
    sched, ds, ms, picker = _cluster(8, rs)
    eps = ds.endpoints()
    sick = sorted(eps, key=lambda e: e.slot)[:2]          # >= 25% of pool
    sick_ips = {e.hostport.split(":")[0] for e in sick}
    sick_hostports = {e.hostport for e in sick}

    # JIT warm-up OUTSIDE the fault window: the first pick compiles the
    # device cycle (seconds) — armed first, the bounded fault schedule
    # would burn out and the breakers re-close before a wave ever ran.
    picker.pick(PickRequest(headers={}, body=b"x"), eps)
    faults.install(FaultInjector(101, {
        "scrape.fetch": FaultRule(p_error=1.0, keys=tuple(sick_ips),
                                  max_fires=12),
    }))
    eng = ScrapeEngine(ms, interval_s=0.01, max_backoff_s=0.04,
                       fetcher=lambda u: VLLM_TEXT, workers=2,
                       breaker_board=rs.board)
    try:
        for e in eps:
            eng.attach(e.slot, f"http://{e.hostport}/metrics", VLLM)
        # The correlated failure opens both sick endpoints' breakers.
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and rs.board.open_count() < 2:
            time.sleep(0.01)
        assert rs.board.open_count() == 2, "breakers never opened"
        # The EPP keeps serving, and routes AROUND the quarantined pods.
        for _ in range(6):
            res = picker.pick(PickRequest(headers={}, body=b"x"),
                              ds.endpoints())
            assert res.endpoint not in sick_hostports
            assert not sick_hostports & set(res.fallbacks)
        # The fault schedule exhausts; scrapes succeed again; the
        # breakers half-open on their dwell and close hysteretically.
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and rs.board.has_open:
            time.sleep(0.01)
        assert not rs.board.has_open, "breakers never re-closed"
        assert rs.board.state(sick[0].slot) == BreakerState.CLOSED
        # Post-recovery picks may use the whole pool again.
        res = picker.pick(PickRequest(headers={}, body=b"x"),
                          ds.endpoints())
        assert ":" in res.endpoint
    finally:
        eng.close()
        picker.close()


# --------------------------------------------------------------------------
# Scenario: metrics blackout -> ROUND_ROBIN floor -> hysteretic lift
# --------------------------------------------------------------------------


def test_metrics_blackout_floors_ladder_and_lifts_on_recovery():
    board = BreakerBoard(BreakerConfig(open_after=1000))  # not the subject
    rs = ResilienceState(board=board, ladder=_fast_ladder())
    rs.ladder.on_change = lambda r: own_metrics.DEGRADED_MODE.set(r)
    own_metrics.DEGRADED_MODE.set(0)
    sched, ds, ms, picker = _cluster(4, rs)

    # JIT warm-up outside the fault window (see the correlated-death
    # scenario): the bounded blackout must develop while waves flow.
    picker.pick(PickRequest(headers={}, body=b"x"), ds.endpoints())
    faults.install(FaultInjector(202, {
        # Every endpoint goes dark after its first successful scrape.
        "scrape.fetch": FaultRule(p_error=1.0, after=1, max_fires=30),
    }))
    eng = ScrapeEngine(ms, interval_s=0.01, max_backoff_s=0.04,
                       fetcher=lambda u: VLLM_TEXT, workers=2,
                       breaker_board=board)
    rs.staleness_fn = eng.staleness_seconds
    try:
        for e in ds.endpoints():
            eng.attach(e.slot, f"http://{e.hostport}/metrics", VLLM)
        served = 0
        deadline = time.monotonic() + 6.0
        # Continuous pick load while the blackout develops: the ladder
        # must floor at ROUND_ROBIN without a single failed pick.
        while (time.monotonic() < deadline
               and rs.ladder.rung() != Rung.ROUND_ROBIN):
            res = picker.pick(PickRequest(headers={}, body=b"x"),
                              ds.endpoints())
            assert ":" in res.endpoint
            served += 1
            time.sleep(0.005)
        assert rs.ladder.rung() == Rung.ROUND_ROBIN, "blackout never floored"
        assert _degraded_gauge() == 2.0       # gie_degraded_mode follows
        # Picks keep flowing while degraded.
        for _ in range(5):
            assert ":" in picker.pick(
                PickRequest(headers={}, body=b"x"), ds.endpoints()).endpoint
        # The fault schedule dries up, scrapes land again, staleness
        # falls under the recovery fraction, and the floor LIFTS.
        deadline = time.monotonic() + 6.0
        while (time.monotonic() < deadline
               and rs.ladder.rung() != Rung.FULL):
            assert ":" in picker.pick(
                PickRequest(headers={}, body=b"x"), ds.endpoints()).endpoint
            time.sleep(0.005)
        assert rs.ladder.rung() == Rung.FULL, "blackout floor never lifted"
        assert _degraded_gauge() == 0.0
        # The transition trace shows down AND back up: 2 -> 0.
        rungs = [r for _, r in rs.ladder.transitions]
        assert 2 in rungs and rungs[-1] == 0
    finally:
        eng.close()
        picker.close()


# --------------------------------------------------------------------------
# Scenario: device dispatch failure -> CACHED descent -> probe recovery
# --------------------------------------------------------------------------


def _run_device_chaos(seed: int):
    rs = ResilienceState(ladder=_fast_ladder(), on_change=None)
    rs.ladder.on_change = None
    sched, ds, ms, picker = _cluster(3, rs)
    faults.install(FaultInjector(seed, {
        "device.dispatch": FaultRule(p_error=1.0, after=2, max_fires=4),
    }))
    try:
        outcomes = []
        deepest = Rung.FULL
        for _ in range(30):
            res = picker.pick(PickRequest(headers={}, body=b"x"),
                              ds.endpoints())
            outcomes.append(res.endpoint)
            deepest = max(deepest, rs.ladder.rung())
            if rs.ladder.rung() != Rung.FULL:
                time.sleep(0.02)   # give probes their cadence
        deadline = time.monotonic() + 5.0
        while (time.monotonic() < deadline
               and rs.ladder.rung() != Rung.FULL):
            picker.pick(PickRequest(headers={}, body=b"x"), ds.endpoints())
            time.sleep(0.02)
        log = list(faults.installed().log)
        return outcomes, deepest, rs.ladder.rung(), log
    finally:
        picker.close()
        faults.uninstall()


def test_device_dispatch_chaos_degrades_recovers_and_is_deterministic():
    outcomes, deepest, final, log1 = _run_device_chaos(seed=7)
    # Every pick was served (30 picks, zero failures)...
    assert len(outcomes) == 30 and all(":" in e for e in outcomes)
    # ...the ladder genuinely descended on the dispatch errors...
    assert deepest >= Rung.CACHED
    # ...and hysteretically climbed back to FULL once the device healed.
    assert final == Rung.FULL
    assert log1, "the schedule must actually have fired"
    assert all(p == "device.dispatch" for p, _k, _v in log1)
    # Identical seed -> bit-identical fault schedule (single dispatcher
    # thread: the global log order IS the per-stream order).
    _outcomes2, _deepest2, _final2, log2 = _run_device_chaos(seed=7)
    assert log1 == log2
    # A different seed draws the same all-error schedule here (p=1.0) —
    # determinism is about the schedule, not the probabilities.


# --------------------------------------------------------------------------
# Scenario: replication partition -> backoff -> catch-up
# --------------------------------------------------------------------------


def _run_partition(seed: int):
    from gie_tpu.replication import FollowerSync, StatePublisher
    from gie_tpu.replication import follower as fol_mod

    state = {"x": np.arange(4.0)}
    pub = StatePublisher({"s": lambda: dict(state)}, era="era-chaos")
    pub.refresh()
    fol = FollowerSync(
        lambda: "mem://", lambda s, delta: True, interval_s=0.05,
        fetch=lambda *a: pub.serve(since=a[1], era=a[2],
                                   if_none_match=a[3]),
        seed=3)
    faults.install(FaultInjector(seed, {
        "replication.poll": FaultRule(p_error=1.0, after=1, max_fires=5),
    }))
    try:
        # Driven on an explicit clock so the backoff-gated cadence is
        # observable: each poll runs exactly when its window opens.
        clock = 100.0
        outcomes = [fol.poll_once(now=clock)]  # healthy: installs epoch 1
        assert outcomes[0] == fol_mod.INSTALLED
        # Partition: the leader keeps publishing while polls fail.
        gaps = []
        for _ in range(5):
            state["x"] = state["x"] + 1.0
            pub.refresh()
            gaps.append(fol._next_poll - clock)
            clock = fol._next_poll
            outcomes.append(fol.poll_once(now=clock))
        assert outcomes[1:] == [fol_mod.FETCH_ERROR] * 5
        # The shared backoff policy stretched the poll cadence: each
        # failed poll's window is at least as long as the last (jittered
        # doubling toward the cap).
        assert fol._backoff.failures == 5
        assert fol._next_poll - clock > gaps[1]
        # Partition heals: the follower catches up to the NEWEST epoch.
        clock = fol._next_poll
        outcomes.append(fol.poll_once(now=clock))
        assert outcomes[-1] == fol_mod.INSTALLED
        assert fol.installed_epoch == pub.status()["epoch"]
        assert fol.fetch_errors == 5
        return outcomes, list(faults.installed().log)
    finally:
        faults.uninstall()


def test_replication_partition_backs_off_and_catches_up():
    out1, log1 = _run_partition(seed=11)
    out2, log2 = _run_partition(seed=11)
    assert out1 == out2 and log1 == log2      # bit-for-bit reproducible


# --------------------------------------------------------------------------
# Scenario: kube-API outage -> actuation error -> next-cycle success
# --------------------------------------------------------------------------


def test_kube_api_outage_survives_and_heals():
    from gie_tpu.autoscale.actuator import ReplicaActuator
    from gie_tpu.autoscale.recommender import Recommendation

    patched = []

    class _Client:
        def _json(self, method, path, body=None, content_type=None):
            patched.append(path)
            return {}

    faults.install(FaultInjector(31, {
        "kube.patch": FaultRule(p_error=1.0, max_fires=3),
    }))
    act = ReplicaActuator(_Client(), "default", target="pool")
    rec = Recommendation(at=0.0, current=2, desired=4, reason="chaos")
    # Outage: all three in-call attempts fail; the loop survives with
    # an "error" outcome instead of raising into the control loop.
    assert act.apply(rec) == "error"
    assert patched == []
    # Next control cycle: the outage ended (schedule exhausted).
    assert act.apply(rec) == "patched"
    assert len(patched) == 1


# --------------------------------------------------------------------------
# Scenario: slow + hung endpoints (per-endpoint latency injection)
# --------------------------------------------------------------------------


def test_slow_and_hung_endpoints_do_not_starve_healthy_peers():
    ms = MetricsStore()
    board = BreakerBoard()
    faults.install(FaultInjector(17, {
        "endpoint.slow": FaultRule(p_latency=1.0, latency_s=0.03,
                                   keys=("10.3.0.1",)),
        "endpoint.hang": FaultRule(p_hang=1.0, hang_s=0.25,
                                   keys=("10.3.0.2",), max_fires=2),
    }))
    eng = ScrapeEngine(ms, interval_s=0.01, fetcher=lambda u: VLLM_TEXT,
                       workers=2, breaker_board=board)
    try:
        eng.attach(0, "http://10.3.0.1:8000/metrics", VLLM)  # slow
        eng.attach(1, "http://10.3.0.2:8000/metrics", VLLM)  # hangs
        eng.attach(2, "http://10.3.0.3:8000/metrics", VLLM)  # healthy
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and not all(
                ms._has_data[s] for s in (0, 1, 2)):
            time.sleep(0.01)
        # Slow and hung endpoints still land rows (latency, not loss),
        # and the healthy peer was never starved by them.
        assert all(ms._has_data[s] for s in (0, 1, 2))
        inj = faults.installed()
        assert inj.fired.get("endpoint.slow", 0) > 0
        assert inj.fired.get("endpoint.hang", 0) == 2
        assert not board.has_open        # latency is not failure
    finally:
        eng.close()


# --------------------------------------------------------------------------
# Slow soak: mixed faults over the composed stack
# --------------------------------------------------------------------------


@pytest.mark.slow
def test_mixed_fault_soak_serves_continuously():
    """~8s of mixed chaos — scrape failures, device dispatch errors,
    per-endpoint latency — against continuous pick load from two
    threads: zero failed picks, bounded degradation, full recovery.

    The schedule is REPLAYED from the shipped mixed-soak scenario file
    (resilience/scenarios/mixed-soak.json) rather than re-declared here:
    the same file reproduces the soak's conditions against a live stack
    via ``--fault-scenario mixed-soak``."""
    from gie_tpu.resilience import scenarios

    scn = scenarios.load("mixed-soak")
    rs = ResilienceState(
        board=BreakerBoard(BreakerConfig(open_after=3, open_s=0.2,
                                         close_after=2)),
        ladder=_fast_ladder(blackout_stale_s=1.0))
    sched, ds, ms, picker = _cluster(scn.drive["pods"], rs)
    scn.arm()
    eng = ScrapeEngine(ms, interval_s=0.01, max_backoff_s=0.05,
                       fetcher=lambda u: VLLM_TEXT, workers=2,
                       breaker_board=rs.board)
    rs.staleness_fn = eng.staleness_seconds
    errors: list = []
    served = [0, 0]
    stop = threading.Event()

    def load(i):
        while not stop.is_set():
            try:
                res = picker.pick(PickRequest(headers={}, body=b"x"),
                                  ds.endpoints())
                assert ":" in res.endpoint
                served[i] += 1
            except Exception as e:  # noqa: BLE001 - the soak's subject
                errors.append(e)
            time.sleep(0.002)

    try:
        for e in ds.endpoints():
            eng.attach(e.slot, f"http://{e.hostport}/metrics", VLLM)
        threads = [threading.Thread(target=load, args=(i,))
                   for i in range(2)]
        [t.start() for t in threads]
        time.sleep(scn.drive["duration_s"])
        stop.set()
        [t.join(timeout=10) for t in threads]
        assert not errors, f"picks failed under chaos: {errors[:3]}"
        assert sum(served) > 200, "load generator barely ran"
        # The schedule genuinely exercised the stack.
        inj = faults.installed()
        assert inj.fired.get("device.dispatch", 0) > 5
        assert inj.fired.get("scrape.fetch", 0) > 20
        # Chaos off: the ladder must return to FULL and breakers close.
        faults.uninstall()
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline and (
                rs.ladder.rung() != Rung.FULL or rs.board.has_open):
            picker.pick(PickRequest(headers={}, body=b"x"), ds.endpoints())
            time.sleep(0.02)
        assert rs.ladder.rung() == Rung.FULL
        assert not rs.board.has_open
    finally:
        stop.set()
        eng.close()
        picker.close()
