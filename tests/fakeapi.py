"""In-process HTTP kube-apiserver for adapter tests.

Speaks just enough of the Kubernetes REST protocol to drive
`controller/kube.py`'s REAL request/watch/resync code paths (VERDICT r3
#5: the adapter had only ever seen duck-typed dicts):

- GET list with a collection resourceVersion + `items`
- GET single object (404 as a Status body)
- chunked `?watch=1&resourceVersion=N` streams (one JSON event per line,
  delivered live as objects change, closed after `timeoutSeconds`)
- 410 Gone when the requested resourceVersion predates the retained
  event window (`compact()` forces this — the relist path)
- PATCH .../status (merge-patch recorded and applied)
- PATCH on apps/v1 Deployments (the autoscale actuator's SSA replica
  patch; applied as a recursive merge — JSON is valid YAML, so the
  apply-patch+yaml body parses as-is)
- coordination.k8s.io/v1 Lease GET/POST/PUT with resourceVersion
  optimistic concurrency (409 on mismatch) — the leader-election
  substrate (reference internal/runnable/leader_election.go uses the
  same Lease semantics through client-go)

Event-log model mirrors etcd: a single monotonically increasing
resourceVersion, per-object rv stamped on every write, watches replay
retained events after their rv then stream live.
"""

from __future__ import annotations

import copy
import http.server
import json
import threading


def _merge(dst: dict, patch: dict) -> None:
    """RFC 7386 merge-patch: objects merge recursively, null deletes,
    everything else replaces. The autoscale actuator's single-field SSA
    patch (spec.replicas) must not wipe the rest of a Deployment's spec."""
    for k, v in patch.items():
        if isinstance(v, dict) and isinstance(dst.get(k), dict):
            _merge(dst[k], v)
        elif v is None:
            dst.pop(k, None)
        else:
            dst[k] = v


class FakeKubeApiServer:
    def __init__(self, retention: int = 1024, port: int = 0):
        self._lock = threading.Condition()
        self._rv = 0
        # path key: ("pods"|"pools"|"services"|"leases", ns, name) -> dict
        self._objects: dict[tuple[str, str, str], dict] = {}
        # retained event log: (rv, resource, event-dict)
        self._events: list[tuple[int, str, dict]] = []
        self._oldest_rv = 0
        self.retention = retention
        self.status_patches: list[tuple[str, str, dict]] = []

        outer = self

        class Handler(http.server.BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def do_GET(self):
                outer._handle_get(self)

            def do_PATCH(self):
                outer._handle_patch(self)

            def do_POST(self):
                outer._handle_put_post(self, create=True)

            def do_PUT(self):
                outer._handle_put_post(self, create=False)

            def do_DELETE(self):
                outer._handle_delete(self)

        self._httpd = http.server.ThreadingHTTPServer(("127.0.0.1", port),
                                                      Handler)
        threading.Thread(target=self._httpd.serve_forever,
                         daemon=True).start()

    @property
    def url(self) -> str:
        host, port = self._httpd.server_address
        return f"http://{host}:{port}"

    def close(self) -> None:
        self._httpd.shutdown()
        # Release the listening socket too, so a test can rebind the port
        # (shutdown() alone only stops serve_forever).
        self._httpd.server_close()

    # -- object mutation (test driver side) --------------------------------

    def _bump(self, resource: str, ev_type: str, obj: dict) -> None:
        """Caller holds the lock."""
        self._rv += 1
        obj.setdefault("metadata", {})["resourceVersion"] = str(self._rv)
        self._events.append((self._rv, resource, {
            "type": ev_type, "object": copy.deepcopy(obj)}))
        if len(self._events) > self.retention:
            self._events = self._events[-self.retention:]
            self._oldest_rv = self._events[0][0] - 1
        self._lock.notify_all()

    def apply(self, resource: str, obj: dict) -> None:
        """Create-or-update; emits ADDED/MODIFIED."""
        meta = obj.setdefault("metadata", {})
        key = (resource, meta.get("namespace", "default"),
               meta.get("name", ""))
        with self._lock:
            ev = "MODIFIED" if key in self._objects else "ADDED"
            self._objects[key] = obj
            self._bump(resource, ev, obj)

    def delete(self, resource: str, namespace: str, name: str) -> None:
        key = (resource, namespace, name)
        with self._lock:
            obj = self._objects.pop(key, None)
            if obj is not None:
                self._bump(resource, "DELETED", obj)

    def compact(self) -> None:
        """Drop every retained event: the next watch from an old
        resourceVersion gets 410 Gone and must relist."""
        with self._lock:
            self._events = []
            self._oldest_rv = self._rv

    # -- request routing ---------------------------------------------------

    @staticmethod
    def _route(path: str):
        """-> (resource, namespace, name|None, subresource|None)."""
        parts = [p for p in path.split("?")[0].split("/") if p]
        # /api/v1/namespaces/{ns}/{pods|services}[/name]
        if parts[:2] == ["api", "v1"] and parts[2] == "namespaces":
            ns, kind = parts[3], parts[4]
            rest = parts[5:]
        # /apis/{group}/{version}/namespaces/{ns}/{plural}[/name[/status]]
        elif parts[0] == "apis" and parts[3] == "namespaces":
            ns, kind = parts[4], parts[5]
            rest = parts[6:]
        else:
            return None
        resource = {"pods": "pods", "services": "services",
                    "inferencepools": "pools", "leases": "leases",
                    "deployments": "deployments",
                    # Multi-cluster federation (gie-fed): the
                    # InferencePoolImport CRD the ClusterSet controller
                    # materializes in importing member clusters.
                    "inferencepoolimports": "imports"}.get(kind)
        if resource is None:
            return None
        name = rest[0] if rest else None
        sub = rest[1] if len(rest) > 1 else None
        return resource, ns, name, sub

    @staticmethod
    def _query(path: str) -> dict:
        if "?" not in path:
            return {}
        out = {}
        for pair in path.split("?", 1)[1].split("&"):
            k, _, v = pair.partition("=")
            out[k] = v
        return out

    def _send_json(self, handler, code: int, body: dict) -> None:
        data = json.dumps(body).encode()
        handler.send_response(code)
        handler.send_header("Content-Type", "application/json")
        handler.send_header("Content-Length", str(len(data)))
        handler.end_headers()
        handler.wfile.write(data)

    def _send_404(self, handler) -> None:
        self._send_json(handler, 404, {
            "kind": "Status", "status": "Failure", "code": 404,
            "reason": "NotFound"})

    # -- GET: single / list / watch ---------------------------------------

    def _handle_get(self, handler) -> None:
        route = self._route(handler.path)
        if route is None:
            return self._send_404(handler)
        resource, ns, name, _sub = route
        q = self._query(handler.path)
        if name is not None:
            with self._lock:
                obj = self._objects.get((resource, ns, name))
            if obj is None:
                return self._send_404(handler)
            return self._send_json(handler, 200, obj)
        if q.get("watch") in ("1", "true"):
            return self._handle_watch(handler, resource, ns, q)
        with self._lock:
            items = [copy.deepcopy(o) for (r, n, _), o in
                     self._objects.items() if r == resource and n == ns]
            rv = self._rv
        self._send_json(handler, 200, {
            "kind": "List", "metadata": {"resourceVersion": str(rv)},
            "items": items})

    def _handle_watch(self, handler, resource, ns, q) -> None:
        try:
            since = int(q.get("resourceVersion", "0") or "0")
        except ValueError:
            since = 0
        timeout_s = float(q.get("timeoutSeconds", "5") or "5")
        handler.send_response(200)
        handler.send_header("Content-Type", "application/json")
        handler.send_header("Transfer-Encoding", "chunked")
        handler.end_headers()

        def send_line(obj: dict) -> bool:
            data = json.dumps(obj).encode() + b"\n"
            try:
                handler.wfile.write(
                    f"{len(data):x}\r\n".encode() + data + b"\r\n")
                handler.wfile.flush()
                return True
            except (BrokenPipeError, ConnectionResetError, OSError):
                return False

        import time

        deadline = time.monotonic() + timeout_s
        sent_rv = since
        with self._lock:
            if since < self._oldest_rv:
                # The requested window was compacted: 410 Gone.
                send_line({"type": "ERROR", "object": {
                    "kind": "Status", "code": 410,
                    "message": "too old resource version"}})
                try:
                    handler.wfile.write(b"0\r\n\r\n")
                except OSError:
                    pass
                return
            while True:
                pending = [
                    ev for rv, res, ev in self._events
                    if rv > sent_rv and res == resource
                    and (ev["object"].get("metadata") or {}).get(
                        "namespace", "default") == ns
                ]
                for ev in pending:
                    if not send_line(ev):
                        return
                if self._events:
                    sent_rv = max(sent_rv, self._events[-1][0])
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._lock.wait(min(remaining, 0.25))
        try:
            handler.wfile.write(b"0\r\n\r\n")
        except OSError:
            pass

    # -- PATCH: status subresource ----------------------------------------

    def _handle_patch(self, handler) -> None:
        route = self._route(handler.path)
        if route is None:
            return self._send_404(handler)
        resource, ns, name, sub = route
        n = int(handler.headers.get("Content-Length", 0) or 0)
        patch = json.loads(handler.rfile.read(n) or b"{}")
        with self._lock:
            obj = self._objects.get((resource, ns, name))
            if obj is None:
                return self._send_404(handler)
            if sub == "status":
                self.status_patches.append((ns, name, patch))
            _merge(obj, patch)
            self._bump(resource, "MODIFIED", obj)
            out = copy.deepcopy(obj)
        self._send_json(handler, 200, out)

    # -- DELETE ------------------------------------------------------------

    def _handle_delete(self, handler) -> None:
        route = self._route(handler.path)
        if route is None:
            return self._send_404(handler)
        resource, ns, name, _sub = route
        if name is None:
            return self._send_404(handler)
        with self._lock:
            obj = self._objects.pop((resource, ns, name), None)
            if obj is None:
                return self._send_404(handler)
            self._bump(resource, "DELETED", obj)
        self._send_json(handler, 200, {
            "kind": "Status", "status": "Success", "code": 200})

    # -- POST/PUT: Lease create/update with optimistic concurrency ---------

    def _handle_put_post(self, handler, create: bool) -> None:
        route = self._route(handler.path)
        if route is None:
            return self._send_404(handler)
        resource, ns, name, _sub = route
        n = int(handler.headers.get("Content-Length", 0) or 0)
        body = json.loads(handler.rfile.read(n) or b"{}")
        meta = body.setdefault("metadata", {})
        meta.setdefault("namespace", ns)
        if name is not None:
            meta.setdefault("name", name)
        key = (resource, ns, meta.get("name", ""))
        with self._lock:
            existing = self._objects.get(key)
            if create:
                if existing is not None:
                    return self._send_json(handler, 409, {
                        "kind": "Status", "code": 409,
                        "reason": "AlreadyExists"})
                self._objects[key] = body
                self._bump(resource, "ADDED", body)
                out = copy.deepcopy(body)
            else:
                if existing is None:
                    return self._send_404(handler)
                sent_rv = meta.get("resourceVersion")
                have_rv = (existing.get("metadata") or {}).get(
                    "resourceVersion")
                if sent_rv is not None and sent_rv != have_rv:
                    # Optimistic-concurrency conflict: another writer won.
                    return self._send_json(handler, 409, {
                        "kind": "Status", "code": 409,
                        "reason": "Conflict"})
                self._objects[key] = body
                self._bump(resource, "MODIFIED", body)
                out = copy.deepcopy(body)
        self._send_json(handler, 200 if not create else 201, out)
