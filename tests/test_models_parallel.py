"""Latency predictor + multi-chip sharding tests (8-device CPU mesh)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from gie_tpu.models.latency import (
    NUM_FEATURES,
    LatencyPredictor,
    OnlineTrainer,
    build_features,
    predictor_score_fn,
)
from gie_tpu.sched import ProfileConfig, Scheduler, Weights
from gie_tpu.sched import constants as C
from gie_tpu.utils.testing import make_endpoints, make_requests


def test_predictor_forward_shapes_positive():
    p = LatencyPredictor()
    params = p.init(jax.random.PRNGKey(0))
    feats = jnp.zeros((4, 7, NUM_FEATURES))
    slots = jnp.zeros((4, 7), jnp.int32)
    out = p.predict(params, feats, slots)
    assert out.shape == (4, 7, 2)
    assert (np.asarray(out) >= 0).all()  # softplus output


def test_build_features_grid():
    reqs = make_requests(5, prompt_len=[100.0] * 5)
    eps = make_endpoints(3, queue=[1, 2, 3])
    grid = build_features(reqs, eps, jnp.zeros((C.M_MAX,)))
    assert grid.shape == (5, C.M_MAX, NUM_FEATURES)


def test_online_trainer_reduces_loss():
    """The MLP must actually learn a simple latency relationship online."""
    p = LatencyPredictor()
    trainer = OnlineTrainer(p, batch_size=64)
    rng = np.random.default_rng(0)
    for _ in range(512):
        f = rng.uniform(0, 1, NUM_FEATURES).astype(np.float32)
        # ttft grows with queue depth (feature 3), tpot with kv (feature 4).
        trainer.observe(f, ttft_s=0.1 + 2.0 * f[3], tpot_s=0.01 + 0.05 * f[4])
    first = trainer.train(steps=1)
    for _ in range(30):
        last = trainer.train(steps=5)
    assert first is not None and last is not None
    assert last < first * 0.5


def test_predictor_column_in_scheduler():
    """Scheduler with the learned column enabled compiles and biases picks
    toward predicted-fast endpoints."""
    p = LatencyPredictor()
    trainer = OnlineTrainer(p, batch_size=32)
    sched = Scheduler(
        ProfileConfig(enable_prefix=False),
        weights=Weights.default().replace(
            latency=jnp.float32(2.0),
            queue=jnp.float32(0.0),
            kv_cache=jnp.float32(0.0),
            assumed_load=jnp.float32(0.0),
            lora=jnp.float32(0.0),
        ),
        predictor_fn=predictor_score_fn(p),
        predictor_params=trainer.params,
    )
    # The phase-in gate zeroes the live column until confidence arrives.
    assert float(sched.weights.latency) == 0.0
    assert sched.base_latency_weight == 2.0
    sched.gate_latency_column(1.0)
    assert float(sched.weights.latency) == 2.0
    # Untrained net: still must run end to end and return valid picks.
    eps = make_endpoints(4, queue=[0, 10, 20, 30])
    res = sched.pick(make_requests(8), eps)
    assert (np.asarray(res.indices[:, 0]) >= 0).all()


def test_confidence_phase_in():
    """OnlineTrainer.confidence ramps 0 -> 1 with samples and converged
    loss, and gate_latency_column scales the live weight by it (the
    round-2 ablation's fix: an under-trained column must not dilute the
    heuristic blend)."""
    p = LatencyPredictor()
    trainer = OnlineTrainer(p, batch_size=64, confidence_min_samples=256,
                            confidence_loss_ok=0.05)
    # Never trained: zero confidence regardless of buffered samples.
    assert trainer.confidence() == 0.0
    rng = np.random.default_rng(1)
    for _ in range(128):
        f = rng.uniform(0, 1, NUM_FEATURES).astype(np.float32)
        trainer.observe(f, ttft_s=0.1 + 2.0 * f[3], tpot_s=0.02)
    trainer.train(steps=5)
    half = trainer.confidence()
    # Sample ramp caps confidence at 128/256 even if loss converged.
    assert 0.0 < half <= 0.5
    for _ in range(384):
        f = rng.uniform(0, 1, NUM_FEATURES).astype(np.float32)
        trainer.observe(f, ttft_s=0.1 + 2.0 * f[3], tpot_s=0.02)
    for _ in range(40):
        trainer.train(steps=5)
    full = trainer.confidence()
    assert full > half
    assert trainer._loss_ema is not None

    sched = Scheduler(
        ProfileConfig(enable_prefix=False),
        weights=Weights.default().replace(latency=jnp.float32(3.0)),
        predictor_fn=predictor_score_fn(p),
        predictor_params=trainer.params,
    )
    assert sched.gate_latency_column(0.0) == 0.0
    assert sched.gate_latency_column(0.5) == 1.5
    # Confidence is clipped to [0, 1]: the ceiling is the configured weight.
    assert sched.gate_latency_column(7.0) == 3.0
    assert float(sched.weights.latency) == 3.0
    # Gating never recompiles and picks stay valid across weight changes.
    eps = make_endpoints(4, queue=[0, 10, 20, 30])
    res = sched.pick(make_requests(8), eps)
    assert (np.asarray(res.indices[:, 0]) >= 0).all()


def test_dryrun_multichip_8():
    import __graft_entry__ as ge

    assert len(jax.devices()) >= 8
    ge.dryrun_multichip(8)


def test_entry_compiles_single_chip():
    import __graft_entry__ as ge

    fn, args = ge.entry()
    result, state = jax.jit(fn)(*args)
    assert result.indices.shape[0] == 64
    assert (np.asarray(result.status) >= 0).all()


def test_online_training_handoff_to_scheduler():
    """Retrained params flow into the live scorer column without recompiling
    or invalidating the old buffers mid-flight."""
    p = LatencyPredictor()
    trainer = OnlineTrainer(p, batch_size=32)
    sched = Scheduler(
        ProfileConfig(enable_prefix=False),
        weights=Weights.default().replace(latency=jnp.float32(1.0)),
        predictor_fn=predictor_score_fn(p),
        predictor_params=trainer.params,
    )
    eps = make_endpoints(4, queue=[0, 1, 2, 3])
    res1 = sched.pick(make_requests(4), eps)
    rng = np.random.default_rng(1)
    for _ in range(64):
        f = rng.uniform(0, 1, NUM_FEATURES).astype(np.float32)
        trainer.observe(f, ttft_s=f[3], tpot_s=0.01)
    assert trainer.train(steps=3) is not None
    sched.set_predictor_params(trainer.params)
    res2 = sched.pick(make_requests(4), eps)  # must not raise / recompile
    assert (np.asarray(res2.indices[:, 0]) >= 0).all()


def test_checkpoint_save_restore(tmp_path):
    """Predictor params survive a restart (the only durable state,
    SURVEY 5.4)."""
    p = LatencyPredictor()
    t1 = OnlineTrainer(p, batch_size=32)
    rng = np.random.default_rng(2)
    for _ in range(64):
        f = rng.uniform(0, 1, NUM_FEATURES).astype(np.float32)
        t1.observe(f, ttft_s=f[3], tpot_s=0.01)
    t1.train(steps=5)
    ckpt = str(tmp_path / "predictor")
    t1.save(ckpt)
    t2 = OnlineTrainer(LatencyPredictor(), seed=99)
    assert t2.restore(ckpt)
    for a, b in zip(jax.tree.leaves(t1.params), jax.tree.leaves(t2.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))
    assert not OnlineTrainer(LatencyPredictor()).restore(str(tmp_path / "none"))


def test_picker_feedback_trains_predictor():
    """Pick-time features + served feedback flow into the trainer through
    the real batching picker."""
    from gie_tpu.datastore import Datastore
    from gie_tpu.datastore.objects import EndpointPool
    from gie_tpu.metricsio import MetricsStore
    from gie_tpu.sched.batching import BatchingTPUPicker
    from gie_tpu.extproc.server import PickRequest

    p = LatencyPredictor()
    trainer = OnlineTrainer(p, batch_size=8)
    ds = Datastore()
    ds.pool_set(EndpointPool({"app": "x"}, [8000], "default"))
    from gie_tpu.datastore.objects import Pod

    ds.pod_update_or_add(Pod(name="p0", labels={"app": "x"}, ip="10.0.0.1"))
    picker = BatchingTPUPicker(
        Scheduler(), ds, MetricsStore(), max_wait_s=0.001, trainer=trainer
    )
    try:
        for i in range(10):
            res = picker.pick(
                PickRequest(headers={}, body=b"hello %d" % i),
                ds.endpoints(),
            )
            assert res.feedback is not None
            feats, slot, _, hostport = res.feedback
            assert hostport == res.endpoint
            assert slot == res.charged_slot
            assert feats.shape == (NUM_FEATURES,)

            class Ctx:
                pick_result = res

            picker.observe_served(res.endpoint, Ctx())
        assert trainer._n == 10
        assert trainer.train(steps=1) is not None
    finally:
        picker.close()


def test_tpot_head_masked_when_unobserved():
    """TTFT-only samples must not drag the TPOT head to zero."""
    p = LatencyPredictor()
    trainer = OnlineTrainer(p, batch_size=32)
    rng = np.random.default_rng(3)
    # Pre-train TPOT on full observations.
    for _ in range(256):
        f = rng.uniform(0, 1, NUM_FEATURES).astype(np.float32)
        trainer.observe(f, ttft_s=0.5, tpot_s=0.08)
    for _ in range(40):
        trainer.train(steps=5)
    feats = rng.uniform(0, 1, (16, NUM_FEATURES)).astype(np.float32)
    eval_slots = np.zeros((16,), np.int32)
    tpot_before = float(np.mean(np.asarray(
        p.predict(trainer.params, feats, eval_slots))[:, 1]))
    # Now flood with TTFT-only samples (tpot unobserved).
    for _ in range(512):
        f = rng.uniform(0, 1, NUM_FEATURES).astype(np.float32)
        trainer.observe(f, ttft_s=0.5, tpot_s=None)
    for _ in range(40):
        trainer.train(steps=5)
    tpot_after = float(np.mean(np.asarray(
        p.predict(trainer.params, feats, eval_slots))[:, 1]))
    assert tpot_after > tpot_before * 0.5  # head not collapsed toward zero


def test_checkpoint_preserves_confidence(tmp_path):
    """A restarted EPP must not re-zero a converged gated column: the
    checkpoint carries the confidence state (loss EMA + observed count),
    and pre-gate params-only checkpoints restore with zero confidence."""
    from gie_tpu.utils.checkpoint import save_pytree

    p = LatencyPredictor()
    t1 = OnlineTrainer(p, batch_size=64, confidence_min_samples=128)
    rng = np.random.default_rng(5)
    for _ in range(256):
        f = rng.uniform(0, 1, NUM_FEATURES).astype(np.float32)
        t1.observe(f, ttft_s=0.1 + 2.0 * f[3], tpot_s=0.02)
    for _ in range(30):
        t1.train(steps=5)
    assert t1.confidence() > 0.0
    t1.save(str(tmp_path / "ck"))

    t2 = OnlineTrainer(LatencyPredictor(), confidence_min_samples=128)
    assert t2.restore(str(tmp_path / "ck"))
    assert t2.confidence() == pytest.approx(t1.confidence(), rel=1e-5)

    # Legacy layout (bare params pytree) still restores, seeding FULL
    # confidence: the release that wrote it applied the configured weight
    # unconditionally, and an upgrade must not silently zero the column.
    save_pytree(str(tmp_path / "old"), t1.params)
    t3 = OnlineTrainer(LatencyPredictor(), confidence_min_samples=128)
    assert t3.restore(str(tmp_path / "old"))
    assert t3.confidence() == 1.0
