"""Latency predictor + multi-chip sharding tests (8-device CPU mesh)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from gie_tpu.models.latency import (
    NUM_FEATURES,
    LatencyPredictor,
    OnlineTrainer,
    build_features,
    predictor_score_fn,
)
from gie_tpu.sched import ProfileConfig, Scheduler, Weights
from gie_tpu.utils.testing import make_endpoints, make_requests


def test_predictor_forward_shapes_positive():
    p = LatencyPredictor()
    params = p.init(jax.random.PRNGKey(0))
    feats = jnp.zeros((4, 7, NUM_FEATURES))
    out = p.predict(params, feats)
    assert out.shape == (4, 7, 2)
    assert (np.asarray(out) >= 0).all()  # softplus output


def test_build_features_grid():
    reqs = make_requests(5, prompt_len=[100.0] * 5)
    eps = make_endpoints(3, queue=[1, 2, 3])
    grid = build_features(reqs, eps, jnp.zeros((512,)))
    assert grid.shape == (5, 512, NUM_FEATURES)


def test_online_trainer_reduces_loss():
    """The MLP must actually learn a simple latency relationship online."""
    p = LatencyPredictor()
    trainer = OnlineTrainer(p, batch_size=64)
    rng = np.random.default_rng(0)
    for _ in range(512):
        f = rng.uniform(0, 1, NUM_FEATURES).astype(np.float32)
        # ttft grows with queue depth (feature 3), tpot with kv (feature 4).
        trainer.observe(f, ttft_s=0.1 + 2.0 * f[3], tpot_s=0.01 + 0.05 * f[4])
    first = trainer.train(steps=1)
    for _ in range(30):
        last = trainer.train(steps=5)
    assert first is not None and last is not None
    assert last < first * 0.5


def test_predictor_column_in_scheduler():
    """Scheduler with the learned column enabled compiles and biases picks
    toward predicted-fast endpoints."""
    p = LatencyPredictor()
    trainer = OnlineTrainer(p, batch_size=32)
    sched = Scheduler(
        ProfileConfig(enable_prefix=False),
        weights=Weights.default().replace(
            latency=jnp.float32(2.0),
            queue=jnp.float32(0.0),
            kv_cache=jnp.float32(0.0),
            assumed_load=jnp.float32(0.0),
            lora=jnp.float32(0.0),
        ),
        predictor_fn=predictor_score_fn(p),
        predictor_params=trainer.params,
    )
    # Untrained net: still must run end to end and return valid picks.
    eps = make_endpoints(4, queue=[0, 10, 20, 30])
    res = sched.pick(make_requests(8), eps)
    assert (np.asarray(res.indices[:, 0]) >= 0).all()


def test_dryrun_multichip_8():
    import __graft_entry__ as ge

    assert len(jax.devices()) >= 8
    ge.dryrun_multichip(8)


def test_entry_compiles_single_chip():
    import __graft_entry__ as ge

    fn, args = ge.entry()
    result, state = jax.jit(fn)(*args)
    assert result.indices.shape[0] == 64
    assert (np.asarray(result.status) >= 0).all()


def test_online_training_handoff_to_scheduler():
    """Retrained params flow into the live scorer column without recompiling
    or invalidating the old buffers mid-flight."""
    p = LatencyPredictor()
    trainer = OnlineTrainer(p, batch_size=32)
    sched = Scheduler(
        ProfileConfig(enable_prefix=False),
        weights=Weights.default().replace(latency=jnp.float32(1.0)),
        predictor_fn=predictor_score_fn(p),
        predictor_params=trainer.params,
    )
    eps = make_endpoints(4, queue=[0, 1, 2, 3])
    res1 = sched.pick(make_requests(4), eps)
    rng = np.random.default_rng(1)
    for _ in range(64):
        f = rng.uniform(0, 1, NUM_FEATURES).astype(np.float32)
        trainer.observe(f, ttft_s=f[3], tpot_s=0.01)
    assert trainer.train(steps=3) is not None
    sched.set_predictor_params(trainer.params)
    res2 = sched.pick(make_requests(4), eps)  # must not raise / recompile
    assert (np.asarray(res2.indices[:, 0]) >= 0).all()
