"""gie-storm test suite (ISSUE 10, docs/STORM.md).

Three tiers:

  shapes     pure schedule compilation — bit-identical-per-seed arrival
             schedules, the composition algebra (rates multiply,
             decorators chain, control events union), the JSON drive-
             section interpreter.
  outlier    p99 serve-latency outlier ejection — deterministic-clock
             hysteresis unit tests, then a storm run proving a
             consistently-slow endpoint quarantines while a merely-
             loaded one never flaps.
  engine     the composed acceptance storm (flash crowd x rolling
             upgrade x LoRA churn over a device-dispatch chaos burst)
             driven through the REAL stack once per module and asserted
             from its scorecard: zero client-visible 5xx, ladder down-
             and-recovered, sheddable 429s at the peak, goodput/SLO
             scored, artifact written, schedule fingerprint stable.

The slow-marked soak replays storm-soak (diurnal + crowd + upgrade +
autoscale + standby failover probes + mixed chaos) — `make storm-smoke`.
"""

from __future__ import annotations

import dataclasses
import json
import os

import pytest

from gie_tpu.resilience import faults
from gie_tpu.resilience.breaker import (
    SERVE,
    BreakerBoard,
    BreakerConfig,
    BreakerState,
)
from gie_tpu.resilience.outlier import OutlierConfig, OutlierEjector
from gie_tpu.storm import shapes as S
from gie_tpu.storm import scorecard as SC


@pytest.fixture(autouse=True)
def _no_leaked_injector():
    faults.uninstall()
    yield
    faults.uninstall()


# ==========================================================================
# Shapes: schedule determinism + composition algebra
# ==========================================================================


def _program(seed=7, **traffic):
    tc = S.TrafficConfig(base_qps=40.0, duration_s=6.0, **traffic)
    return S.Program(tc, [
        S.FlashCrowd(at_s=2.0, ramp_s=0.5, hold_s=1.5, magnitude=3.0),
        S.LoraChurn(adapters=6, hot=2, rotate_every_s=2.0, p=0.8),
        S.LongContextMix(fraction=0.2, prompt_bytes=4096),
        S.RollingUpgrade(start_s=1.0, pods=4, interval_s=1.0, settle_s=0.5),
    ], seed=seed)


def test_same_seed_bit_identical_schedule():
    s1, s2 = _program(seed=7).compile(), _program(seed=7).compile()
    assert s1.arrivals == s2.arrivals
    assert s1.events == s2.events
    assert s1.fingerprint() == s2.fingerprint()


def test_different_seed_different_schedule():
    s1, s2 = _program(seed=7).compile(), _program(seed=8).compile()
    assert s1.fingerprint() != s2.fingerprint()


def test_rate_composition_multiplies():
    tc = S.TrafficConfig(base_qps=30.0, duration_s=4.0)
    base = S.Program(tc, [], seed=5).compile()
    tripled = S.Program(tc, [S.ConstantRate(3.0)], seed=5).compile()
    ratio = len(tripled.arrivals) / max(len(base.arrivals), 1)
    assert 2.5 < ratio < 3.5
    # Two stacked factors multiply (3 * 2 = 6x).
    six = S.Program(
        tc, [S.ConstantRate(3.0), S.ConstantRate(2.0)], seed=5).compile()
    # Wide bounds: the deterministic Poisson draw still carries sampling
    # variance relative to the base program's own draw.
    assert 4.5 < len(six.arrivals) / max(len(base.arrivals), 1) < 7.5


def test_flash_crowd_elevates_its_window_only():
    crowd = S.FlashCrowd(at_s=2.0, ramp_s=0.5, hold_s=1.5, magnitude=4.0)
    assert crowd.rate(0.0) == 1.0
    assert crowd.rate(2.25) == pytest.approx(2.5)   # mid-ramp
    assert crowd.rate(3.0) == 4.0                   # hold
    assert crowd.rate(10.0) == 1.0                  # passed
    tc = S.TrafficConfig(base_qps=40.0, duration_s=6.0)
    sched = S.Program(tc, [crowd], seed=3).compile()
    lo, hi = crowd.window()
    inside = sum(1 for a in sched.arrivals if lo <= a.t < hi)
    per_s_in = inside / (hi - lo)
    outside = len(sched.arrivals) - inside
    per_s_out = outside / (tc.duration_s - (hi - lo))
    assert per_s_in > 2.0 * per_s_out


def test_diurnal_ramp_floor_and_peak():
    d = S.DiurnalRamp(period_s=10.0, floor=0.25, peak=1.0)
    assert d.rate(0.0) == pytest.approx(0.25)    # valley
    assert d.rate(5.0) == pytest.approx(1.0)     # mid-period peak
    assert d.rate(10.0) == pytest.approx(0.25)   # next valley


def test_lora_churn_hot_set_rotates_and_bounds_adapters():
    churn = S.LoraChurn(adapters=6, hot=2, rotate_every_s=2.0, p=1.0)
    assert churn.hot_set(0.0) != churn.hot_set(2.0)
    sched = S.Program(
        S.TrafficConfig(base_qps=40.0, duration_s=6.0),
        [churn], seed=9).compile()
    with_lora = [a for a in sched.arrivals if a.lora is not None]
    assert with_lora, "p=1.0 churn produced no adapter traffic"
    for a in with_lora:
        assert a.lora in churn.hot_set(
            (a.t // churn.rotate_every_s) * churn.rotate_every_s)


def test_long_context_mix_fraction_and_attributes():
    mix = S.LongContextMix(fraction=0.25, prompt_bytes=8192,
                           decode_scale=2.0)
    sched = S.Program(
        S.TrafficConfig(base_qps=60.0, duration_s=5.0),
        [mix], seed=4).compile()
    long = [a for a in sched.arrivals if a.kind == "long_context"]
    frac = len(long) / len(sched.arrivals)
    assert 0.15 < frac < 0.35
    assert all(a.prompt_bytes == 8192 for a in long)


def test_rolling_upgrade_events_pair_and_order():
    up = S.RollingUpgrade(start_s=1.0, pods=3, interval_s=1.0,
                          settle_s=0.4)
    events = up.control_events(duration_s=10.0)
    assert [(e.kind, e.args[0]) for e in events] == [
        ("drain", 0), ("replace", 0), ("drain", 1), ("replace", 1),
        ("drain", 2), ("replace", 2)]
    # A step the run cannot finish is skipped, never half-applied.
    short = up.control_events(duration_s=2.3)
    assert [(e.kind, e.args[0]) for e in short] == [
        ("drain", 0), ("replace", 0)]
    with pytest.raises(ValueError, match="settle_s"):
        S.RollingUpgrade(interval_s=1.0, settle_s=1.0)


def test_control_events_union_sorted_across_shapes():
    tc = S.TrafficConfig(base_qps=10.0, duration_s=8.0)
    sched = S.Program(tc, [
        S.RollingUpgrade(start_s=1.0, pods=2, interval_s=2.0, settle_s=1.0),
        S.StandbyFailover(every_s=3.0, start_s=0.5),
    ], seed=1).compile()
    kinds = {e.kind for e in sched.events}
    assert kinds == {"drain", "replace", "failover_check"}
    assert [e.t for e in sched.events] == sorted(e.t for e in sched.events)


def test_shapes_from_specs_registry():
    built = S.shapes_from_specs([
        {"kind": "flash_crowd", "at_s": 1.0, "magnitude": 2.0},
        {"kind": "lora_churn", "adapters": 4},
    ])
    assert isinstance(built[0], S.FlashCrowd)
    assert isinstance(built[1], S.LoraChurn)
    with pytest.raises(ValueError, match="unknown storm shape"):
        S.shapes_from_specs([{"kind": "nope"}])
    with pytest.raises(ValueError, match="bad kwargs"):
        S.shapes_from_specs([{"kind": "flash_crowd", "wat": 1}])
    with pytest.raises(ValueError, match="kind"):
        S.shapes_from_specs(["flash_crowd"])


def test_program_from_drive_rejects_unknown_traffic_fields():
    with pytest.raises(ValueError, match="unknown storm traffic"):
        S.program_from_drive(
            {"base_qps": 10, "duration_s": 2,
             "traffic": {"qqps": 1}}, seed=0)


# ==========================================================================
# Tenant shapes (gie-fair, ISSUE 11): Zipf mix, pinned VIP, abuser algebra
# ==========================================================================


def test_tenant_mix_zipf_head_heavy_and_bounded():
    mix = S.TenantMix(tenants=5, zipf_a=1.2)
    sched = S.Program(
        S.TrafficConfig(base_qps=60.0, duration_s=5.0), [mix],
        seed=6).compile()
    assert all(a.tenant is not None for a in sched.arrivals)
    counts = {}
    for a in sched.arrivals:
        counts[a.tenant] = counts.get(a.tenant, 0) + 1
    assert set(counts) <= {f"t{k}" for k in range(5)}
    assert counts["t0"] > counts.get("t4", 0), counts  # head-heavy


def test_pinned_tenant_owns_share_and_band():
    sched = S.Program(
        S.TrafficConfig(base_qps=60.0, duration_s=5.0, critical_fraction=0.0),
        [S.TenantMix(tenants=4), S.PinnedTenant("vip", share=0.2,
                                                band="critical")],
        seed=3).compile()
    vip = [a for a in sched.arrivals if a.tenant == "vip"]
    frac = len(vip) / len(sched.arrivals)
    assert 0.12 < frac < 0.3, frac
    assert all(a.band == "critical" for a in vip)
    # Nobody else inherited the pinned band.
    assert not [a for a in sched.arrivals
                if a.tenant != "vip" and a.band == "critical"]


def test_abusive_tenant_rate_algebra_preserves_victims():
    """The noisy-neighbor contract: inside the abuse window the abuser's
    own rate is ~rate_x times its base share while every OTHER tenant's
    absolute arrival rate stays unchanged — and stolen arrivals re-draw
    the abuser's band mix, never keeping a victim's CRITICAL band."""
    abuse = S.AbusiveTenant("abuser", share=0.2, rate_x=10.0, at_s=0.0,
                            ramp_s=0.0, hold_s=100.0,
                            sheddable_fraction=1.0)
    assert abuse.rate(1.0) == pytest.approx(1.0 + 0.2 * 9.0)
    tc = S.TrafficConfig(base_qps=60.0, duration_s=6.0,
                         critical_fraction=0.0, sheddable_fraction=0.0)
    base = S.Program(tc, [S.TenantMix(tenants=3)], seed=12).compile()
    stormy = S.Program(
        tc, [S.TenantMix(tenants=3),
             S.PinnedTenant("vip", share=0.1, band="critical"),
             abuse],
        seed=12).compile()
    n_abuse = sum(1 for a in stormy.arrivals if a.tenant == "abuser")
    others = len(stormy.arrivals) - n_abuse
    # Victims' absolute volume ~= the no-abuse compile's volume (same
    # seed; the Poisson draws differ, so bounds are loose).
    assert 0.75 < others / len(base.arrivals) < 1.25
    # The abuser carries ~share*rate_x/(1+share*(x-1)) of the total.
    frac = n_abuse / len(stormy.arrivals)
    assert 0.55 < frac < 0.85, frac
    # Stolen arrivals re-drew the abuser band mix: no critical abuser.
    assert all(a.band == "sheddable"
               for a in stormy.arrivals if a.tenant == "abuser")


def test_tenant_shapes_in_registry():
    built = S.shapes_from_specs([
        {"kind": "tenant_mix", "tenants": 4},
        {"kind": "pinned_tenant", "tenant": "vip", "share": 0.1},
        {"kind": "abusive_tenant", "tenant": "x", "share": 0.1,
         "rate_x": 5.0},
    ])
    assert isinstance(built[0], S.TenantMix)
    assert isinstance(built[1], S.PinnedTenant)
    assert isinstance(built[2], S.AbusiveTenant)


# ==========================================================================
# Engine: the noisy-neighbor isolation storm (ISSUE 11 acceptance)
# ==========================================================================


def _solo_baseline_path(tmp_path) -> str:
    """storm-noisy-neighbor minus the abusive_tenant shape, same seed:
    the victim's solo world."""
    from gie_tpu.resilience import scenarios

    scn = scenarios.load("storm-noisy-neighbor")
    with open(scn.path, "r", encoding="utf-8") as fh:
        raw = json.load(fh)
    raw["name"] = "storm-noisy-neighbor-solo"
    raw["drive"]["storm"]["shapes"] = [
        s for s in raw["drive"]["storm"]["shapes"]
        if s["kind"] != "abusive_tenant"]
    path = str(tmp_path / "storm-noisy-neighbor-solo.json")
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(raw, fh)
    return path


def test_storm_noisy_neighbor_isolates_victim(tmp_path):
    """The ROADMAP item-5 pinned property: one tenant flooding at 20x
    its base rate saturates the pool, the weighted-DRR queue + the
    over-fair-share preemptive shed land the 429s on the ABUSER's
    SHEDDABLE traffic, zero CRITICAL-band sheds happen while lower
    bands hold queued work, and the latency-sensitive CRITICAL victim's
    p99/SLO attainment stay within tolerance of its same-seed solo
    baseline."""
    from gie_tpu.storm.engine import run_scenario

    # virtual_time (gie-twin): the flood executes on the virtual clock,
    # so the submitter can never fall behind it on a loaded box — the
    # seeded-retry wrapper this test used to carry is deleted, because
    # the virtual clock removed the CAUSE (real-time CPU contention).
    result = run_scenario("storm-noisy-neighbor", dump_dir=str(tmp_path))
    card = result.scorecard
    assert card["virtual_time"] is True
    assert card["client_5xx"] == 0, card["client_5xx_detail"]
    assert card["resets"] == 0 and card["timeouts"] == 0
    assert card["shed"] >= 10, (
        "the 20x flood never saturated — not a noisy-neighbor storm")
    # (a) The abuser absorbs the sheds; its own SHEDDABLE band eats them.
    per = card["per_tenant"]
    abuser = per["abuser"]
    assert abuser["shed"] / card["shed"] >= 0.6, per
    assert card["shed_by_band"].get("critical", 0) == 0
    assert card["shed_by_band"].get("sheddable", 0) >= abuser["shed"]
    # (b) CRITICAL never sheds: the vip tenant got every answer.
    vip = per["vip"]
    assert vip["shed"] == 0 and vip["client_5xx"] == 0
    assert vip["completed"] > 5
    # (c) Victim isolation vs the same-seed solo baseline.
    solo = run_scenario(
        _solo_baseline_path(tmp_path),
        dump_dir=str(tmp_path)).scorecard["per_tenant"]["vip"]
    assert solo["completed"] > 5
    assert vip["slo_attainment"] >= solo["slo_attainment"] - 0.2, (
        vip, solo)
    # p99 tolerance: small absolute baselines get an absolute floor; the
    # flood must not push the victim's p99 past its SLO-scale budget.
    assert vip["ttft_p99_s"] <= max(4.0 * solo["ttft_p99_s"],
                                    solo["ttft_p99_s"] + 2.0), (vip, solo)


def test_noisy_neighbor_tenant_zpage_explains_the_abuser():
    """/debugz/tenants end-to-end (ISSUE 11 acceptance): after a
    saturated tenant mix, the picker's tenants_report names the abuser
    over-share, shows its shed rate, and carries the DRR/weight state."""
    from gie_tpu.storm.engine import EngineConfig, PoolSpec, StormEngine

    prog = S.Program(
        S.TrafficConfig(base_qps=40.0, duration_s=4.0,
                        sheddable_fraction=0.5, critical_fraction=0.0),
        [S.TenantMix(tenants=3),
         S.AbusiveTenant("abuser", share=0.15, rate_x=15.0, at_s=0.5,
                         ramp_s=0.5, hold_s=3.0)],
        seed=21)
    eng = StormEngine(prog, pool=PoolSpec(n_pods=3),
                      cfg=EngineConfig(queue_limit=3.0),
                      name="nn-zpage")
    try:
        eng.run()
        rep = eng.picker.tenants_report()
    finally:
        eng.close()
    assert "abuser" in rep["tenants"], rep["tenants"].keys()
    row = rep["tenants"]["abuser"]
    assert row["requests_total"] > 50
    assert row["arrival_cost_w"] >= 0.0
    assert "weights" in rep and "deficits" in rep and "queue" in rep
    # The flood was over-share at SOME point; the report records the
    # windowed view — assert the ledger fields exist and are sane.
    assert 0.0 <= row["shed_rate_w"] <= 1.0


def test_storm_mesh_scheduler_smoke():
    """ISSUE 15 satellite: --mesh-devices serves the PRODUCTION batching
    picker, not just the dryrun — a small storm through Scheduler(mesh=)
    on the CPU virtual mesh (dp x tp sharded cycle, docs/MESH.md) ends
    with zero client 5xx and a valid scorecard."""
    import jax

    from gie_tpu.storm import scorecard as SC
    from gie_tpu.storm.engine import EngineConfig, PoolSpec, StormEngine

    assert len(jax.devices()) >= 8
    prog = S.Program(
        S.TrafficConfig(base_qps=15.0, duration_s=3.0, n_sessions=8),
        [], seed=33)
    eng = StormEngine(
        prog, pool=PoolSpec(n_pods=3),
        cfg=EngineConfig(mesh_devices=8, virtual_time=True),
        name="mesh-smoke")
    try:
        assert eng.scheduler.mesh is not None
        assert dict(eng.scheduler.mesh.shape) == {"dp": 4, "tp": 2}
        result = eng.run()
    finally:
        eng.close()
    card = result.scorecard
    SC.validate(card)
    assert card["client_5xx"] == 0, card["client_5xx"]
    assert card["ok"] > 20
    # The sharded cycle really served the picks (not a fallback path).
    assert eng.picker.scheduler is eng.scheduler


# ==========================================================================
# Outlier ejection: deterministic-clock hysteresis units
# ==========================================================================


class _Clock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


def _ejector(clock, **kw):
    cfg = dict(window_s=8.0, quantile=0.9, ratio=3.0, min_samples=4,
               pool_min_samples=12, breach_streak=2, eval_interval_s=1.0,
               cooldown_s=5.0, max_eject_fraction=0.34, floor_s=0.001)
    cfg.update(kw)
    return OutlierEjector(OutlierConfig(**cfg), clock=clock)


def _feed(ej, clock, latencies_by_slot, n=4):
    for _ in range(n):
        for slot, lat in latencies_by_slot.items():
            ej.note(slot, lat)


def test_outlier_ejects_sustained_slow_endpoint_on_serve_plane():
    clock = _Clock()
    ej = _ejector(clock)
    board = BreakerBoard(BreakerConfig(open_s=30.0), clock=clock)
    pool = {0: 0.05, 1: 0.06, 2: 0.04, 3: 1.0}
    _feed(ej, clock, pool)
    assert ej.evaluate(board) == []          # streak 1: no ejection yet
    clock.t += 1.0
    _feed(ej, clock, pool)
    assert ej.evaluate(board) == [3]         # streak 2: ejected
    assert board.state(3) == BreakerState.OPEN
    assert board.report()["breakers"]["3"]["opened_by"] == SERVE
    assert ej.ejections and ej.ejections[0][1] == 3


def test_outlier_single_spike_does_not_eject():
    # Short window so the spike AGES OUT between evals — a breach must
    # be sustained across consecutive evals to eject, and one spike
    # followed by recovery resets the streak.
    clock = _Clock()
    ej = _ejector(clock, window_s=2.0)
    board = BreakerBoard(clock=clock)
    _feed(ej, clock, {0: 0.05, 1: 0.06, 2: 0.04, 3: 1.0})
    assert ej.evaluate(board) == []          # breach eval #1 (streak 1)
    clock.t += 2.5                           # spike leaves the window
    _feed(ej, clock, {0: 0.05, 1: 0.06, 2: 0.04, 3: 0.05})  # recovered
    assert ej.evaluate(board) == []          # streak reset, not ejected
    clock.t += 2.5
    _feed(ej, clock, {0: 0.05, 1: 0.06, 2: 0.04, 3: 1.0})
    assert ej.evaluate(board) == []          # a fresh streak starts at 1
    assert board.state(3) == BreakerState.CLOSED


def test_outlier_pool_wide_slowdown_ejects_nobody():
    clock = _Clock()
    ej = _ejector(clock)
    board = BreakerBoard(clock=clock)
    slow_everywhere = {0: 2.0, 1: 2.2, 2: 1.8, 3: 2.1}
    for _ in range(4):
        _feed(ej, clock, slow_everywhere)
        assert ej.evaluate(board) == []
        clock.t += 1.0
    assert not board.has_open


def test_outlier_eject_budget_never_empties_the_pool():
    clock = _Clock()
    # Two of three endpoints "slow": the 1/3 budget ejects at most one.
    ej = _ejector(clock, max_eject_fraction=0.34, ratio=2.0)
    board = BreakerBoard(BreakerConfig(open_s=60.0), clock=clock)
    pool = {0: 0.05, 1: 5.0, 2: 5.0}
    for _ in range(4):
        _feed(ej, clock, pool, n=6)
        ej.evaluate(board)
        clock.t += 1.0
    assert board.open_count() <= 1


def test_outlier_cooldown_bounds_reejection_cadence():
    clock = _Clock()
    ej = _ejector(clock, cooldown_s=100.0)
    board = BreakerBoard(BreakerConfig(open_s=0.5, close_after=1),
                         clock=clock)
    pool = {0: 0.05, 1: 0.06, 2: 0.04, 3: 1.0}
    for _ in range(3):
        _feed(ej, clock, pool)
        ej.evaluate(board)
        clock.t += 1.0
    assert len(ej.ejections) == 1
    # The breaker heals (serve-opened probe path)...
    clock.t += 1.0
    board.quarantined(3)                     # dwell elapsed: HALF_OPEN
    board.record_serve_outcome(3, ok=True)
    assert board.state(3) == BreakerState.CLOSED
    # ...and keeps breaching, but the cooldown refuses a re-eject storm.
    for _ in range(4):
        _feed(ej, clock, pool)
        ej.evaluate(board)
        clock.t += 1.0
    assert len(ej.ejections) == 1


def test_outlier_drop_clears_slot_state():
    clock = _Clock()
    ej = _ejector(clock)
    _feed(ej, clock, {0: 0.05, 1: 1.0})
    ej.drop(1)
    assert 1 not in ej.report()["tracked"]
    assert ej.report()["streaks"] == {}


# ==========================================================================
# Flight-recorder schema version (ISSUE 10 satellite; gie_tpu/obs)
# ==========================================================================


def test_flight_recorder_stamps_schema_version():
    from gie_tpu.obs.recorder import SCHEMA_VERSION, FlightRecorder

    rec = FlightRecorder(8)
    published = rec.append({"model": "m", "outcome": "picked"})
    assert published["v"] == SCHEMA_VERSION
    assert all(r["v"] == SCHEMA_VERSION for r in rec.snapshot())


def test_flight_recorder_load_is_tolerant():
    from gie_tpu.obs.recorder import SCHEMA_VERSION, load_records

    dump = json.dumps([
        {"v": SCHEMA_VERSION, "seq": 0, "model": "m"},
        {"seq": 1, "model": "old"},                    # pre-version dump
        {"v": SCHEMA_VERSION + 7, "seq": 2, "brand_new_field": [1, 2]},
        "junk-entry",                                  # tolerated, skipped
    ])
    recs = load_records(dump)
    assert [r["seq"] for r in recs] == [0, 1, 2]
    assert recs[1]["v"] == 0                           # stamped legacy
    assert recs[2]["brand_new_field"] == [1, 2]        # unknown kept
    # Envelope form loads identically.
    assert load_records(json.dumps({"records": [{"seq": 9}]}))[0]["seq"] == 9
    with pytest.raises(ValueError):
        load_records(json.dumps("not-a-dump"))


# ==========================================================================
# Engine: the composed acceptance storm (one run, many assertions)
# ==========================================================================


@pytest.fixture(scope="module")
def composed(tmp_path_factory):
    """ONE storm-flash-upgrade replay through the real stack (flash
    crowd x rolling upgrade x LoRA churn x long-context over a bounded
    device-dispatch chaos burst, autoscale armed), shared by every
    assertion below — the run is the expensive part, the claims are
    cheap reads of its scorecard. Virtual clock: every claim here is a
    scorecard-shape claim, and the real-clock run was flaking its
    zero-5xx assertion when the full suite loaded the CI box (drain
    racing wall time); under the gie-twin clock the same seed gives the
    same card every run. Real-thread coverage stays with the storms
    below that exercise wall-clock behavior on purpose."""
    from gie_tpu import obs
    from gie_tpu.obs.recorder import FlightRecorder
    from gie_tpu.storm.engine import run_scenario

    faults.uninstall()
    obs.install(recorder=FlightRecorder(4096))
    dump_dir = str(tmp_path_factory.mktemp("storm"))
    try:
        result = run_scenario("storm-flash-upgrade", dump_dir=dump_dir,
                              virtual_time=True)
        records = obs.RECORDER.snapshot()
    finally:
        obs.uninstall()
        faults.uninstall()
    return result, records


def test_composed_zero_client_visible_5xx(composed):
    """The ISSUE 10 acceptance core: a full rolling upgrade under
    continuous flash-crowd traffic with chaos layered on top — and not
    one client-visible 5xx, reset, or wedged stream."""
    card = composed[0].scorecard
    assert card["client_5xx"] == 0, card["client_5xx_detail"]
    assert card["resets"] == 0
    assert card["timeouts"] == 0
    assert card["ok"] > 300, "the storm barely served"


def test_composed_upgrade_replaced_every_pod(composed):
    card = composed[0].scorecard
    steps = [(u["step"], u["pod"]) for u in card["upgrades"]]
    assert steps.count(("drain", f"p{0}")) == 1
    assert sum(1 for s, _ in steps if s == "drain") == 6
    assert sum(1 for s, _ in steps if s == "replace") == 6
    # Every original 10.77.* endpoint is gone; replacements serve.
    assert card["final_endpoints"]
    assert not [hp for hp in card["final_endpoints"]
                if hp.startswith("10.77.")]
    # Traffic genuinely reached replacement pods after the upgrade.
    assert composed[0].datastore.endpoints()


def test_composed_ladder_descends_and_recovers(composed):
    """The device-dispatch chaos burst must push the ladder off FULL
    mid-storm, and hysteretic ascent must bring it home after."""
    card = composed[0].scorecard
    assert card["fault_fired"].get("device.dispatch", 0) >= 1
    assert card["max_rung"] >= 1, "the chaos burst never degraded picks"
    assert card["final_rung"] == 0, "the ladder never recovered to FULL"
    rungs = [r for _, r in card["rung_trace"]]
    assert rungs[-1] == 0 and max(rungs) >= 1


def test_composed_goodput_and_slo_scored(composed):
    card = composed[0].scorecard
    assert card["goodput_tokens_per_s"] > 0
    assert 0.0 < card["slo_attainment"] <= 1.0
    assert card["completed"] > 300
    assert card["lora_arrivals"] > 100
    assert card["long_context_arrivals"] > 20


def test_composed_scorecard_schema_and_artifact(composed):
    card = composed[0].scorecard
    SC.validate(card)
    path = card["artifact"]
    assert os.path.exists(path)
    with open(path, "r", encoding="utf-8") as fh:
        loaded = json.load(fh)
    assert loaded["schema"] == SC.SCHEMA
    assert loaded["client_5xx"] == card["client_5xx"]


def test_composed_schedule_bit_identical_per_seed(composed):
    """The replay contract: recompiling the scenario's storm program
    from the file yields the exact arrival schedule the run executed."""
    from gie_tpu.resilience import scenarios

    card = composed[0].scorecard
    scn = scenarios.load("storm-flash-upgrade")
    prog = S.program_from_drive(scn.drive["storm"], seed=scn.seed)
    assert prog.compile().fingerprint() == card["schedule_fingerprint"]
    assert card["seed"] == scn.seed


def test_composed_flight_recorder_explains_the_storm(composed):
    """gie-obs rides along: decision records exist for both the full
    path and the degraded rungs, all stamped with the schema version."""
    from gie_tpu.obs.recorder import SCHEMA_VERSION

    _, records = composed
    assert records, "no decision records published"
    rungs = {r.get("rung") for r in records}
    assert "full" in rungs
    assert rungs - {"full"}, (
        "no degraded-rung records — the chaos burst left no audit trail")
    assert all(r.get("v") == SCHEMA_VERSION for r in records)


def test_composed_pool_capacity_trace(composed):
    card = composed[0].scorecard
    sizes = [n for _, n in card["pool_size_trace"]]
    assert sizes and max(sizes) >= 6
    assert sizes[-1] >= 6, "the pool ended the storm smaller than it began"


# ==========================================================================
# Engine: outlier ejection under a storm (the satellite's storm proof)
# ==========================================================================


def test_storm_outlier_ejects_slow_endpoint_not_loaded_one():
    """A pod serving 2xx at ~20x the pool's first-token latency is
    quarantined by ejection alone (its breaker never sees an error);
    a merely-loaded pod (fewer slots, slower decode — latency within
    the pool's band) is never touched. Hysteresis: ejections are
    cooldown-bounded, not a flap storm."""
    from gie_tpu.storm.engine import (
        DEFAULT_STUB,
        EngineConfig,
        PoolSpec,
        StormEngine,
    )

    slow = dataclasses.replace(
        DEFAULT_STUB, prefill_tokens_per_s=300.0, prefix_cache_chunks=1)
    loaded = dataclasses.replace(
        DEFAULT_STUB, decode_tokens_per_s=28.0, max_running=6)
    fleet = [DEFAULT_STUB] * 4 + [slow, loaded]
    prog = S.Program(
        S.TrafficConfig(base_qps=30.0, duration_s=8.0, n_sessions=12),
        [], seed=11)
    cfg = EngineConfig(ttft_slo_s=3.0, outlier=OutlierConfig(
        window_s=5.0, quantile=0.95, ratio=2.5, min_samples=10,
        pool_min_samples=40, breach_streak=2, eval_interval_s=0.5,
        cooldown_s=3.0))
    eng = StormEngine(prog, pool=PoolSpec(n_pods=6, stub=fleet), cfg=cfg,
                      name="outlier-storm")
    try:
        result = eng.run()
    finally:
        eng.close()
    card = result.scorecard
    slow_slot = eng.datastore.endpoint_by_hostport("10.77.0.5:8000").slot
    loaded_slot = eng.datastore.endpoint_by_hostport("10.77.0.6:8000").slot
    ejected_slots = [e["slot"] for e in card["ejections"]]
    assert slow_slot in ejected_slots, (
        f"the slow endpoint was never ejected: {card['ejections']}")
    assert set(ejected_slots) == {slow_slot}, (
        f"ejection touched healthy endpoints: {card['ejections']}")
    # Hysteresis: cooldown bounds re-ejection cadence (no flap storm).
    assert len(ejected_slots) <= 3
    # The merely-loaded endpoint's breaker never tripped at all.
    loaded_rep = result.board.report()["breakers"].get(str(loaded_slot))
    assert loaded_rep is None or loaded_rep["transitions"] == 0
    # The quarantine came from LATENCY, not errors: zero 5xx all run.
    assert card["client_5xx"] == 0
    slow_rep = result.board.report()["breakers"][str(slow_slot)]
    assert slow_rep["opened_by"] == SERVE


# ==========================================================================
# Engine: overload -> sheddable 429s -> shed-driven autoscale
# ==========================================================================


def test_storm_capacity_sheds_and_scales_under_overload(tmp_path):
    """storm-capacity (docs/STORM.md): a 6x crowd against a 4-pod pool
    with no upgrade escape hatch. Every candidate saturates, so the
    cycle's SHEDDABLE path sheds with 429 (never a 5xx), the sustained
    shed rate drives the real recommender's fast-up, and the pool grows
    — the whole closed capacity loop in one storm."""
    from gie_tpu.storm.engine import run_scenario

    # virtual_time (gie-twin): the crowd executes on the virtual clock —
    # the submitter cannot fall behind it, client_skipped cannot eat the
    # overload, and the seeded-retry wrapper this test used to carry is
    # deleted (the virtual clock removed the CAUSE of the flake, not the
    # symptom).
    result = run_scenario("storm-capacity", dump_dir=str(tmp_path))
    card = result.scorecard
    assert card["virtual_time"] is True
    assert card["client_5xx"] == 0, card["client_5xx_detail"]
    assert card["shed"] > 0, (
        "the 6x crowd never shed sheddable traffic — the overload was "
        "not an overload")
    assert card["goodput_tokens_per_s"] > 0
    sizes = [n for _, n in card["pool_size_trace"]]
    assert max(sizes) > 4, (
        f"the autoscale loop never added capacity: {card['autoscale_events']}")
    assert card["autoscale_events"], "no autoscale decision was recorded"


# ==========================================================================
# Scenario-drive interpretation errors
# ==========================================================================


def test_run_scenario_requires_storm_drive():
    from gie_tpu.storm.engine import run_scenario

    with pytest.raises(ValueError, match="drive.storm"):
        run_scenario("mixed-soak")


def test_storm_scenarios_ship_in_the_library():
    from gie_tpu.resilience import scenarios

    names = scenarios.list_scenarios()
    assert {"storm-flash-upgrade", "storm-soak",
            "storm-noisy-neighbor"} <= set(names)
    for name in ("storm-flash-upgrade", "storm-soak"):
        scn = scenarios.load(name)
        prog = S.program_from_drive(scn.drive["storm"], seed=scn.seed)
        sched = prog.compile()
        assert sched.arrivals and sched.events
    # The noisy-neighbor storm is traffic-only (no control-plane shapes
    # -> no events); its arrivals must carry the tenant decorations.
    scn = scenarios.load("storm-noisy-neighbor")
    sched = S.program_from_drive(scn.drive["storm"],
                                 seed=scn.seed).compile()
    assert sched.arrivals and not sched.events
    tenants = {a.tenant for a in sched.arrivals}
    assert "abuser" in tenants and "vip" in tenants


# ==========================================================================
# gie-twin (ISSUE 14): virtual clock — compression, determinism,
# real-vs-virtual equivalence, long-horizon hysteresis, trace replay
# ==========================================================================


def _hour_program(seed=7171):
    """A one-hour diurnal composition (the acceptance storm: >= 1 h of
    simulated time, low enough rate that TWO runs fit the CI budget)."""
    return S.Program(
        S.TrafficConfig(base_qps=0.5, duration_s=3600.0, n_sessions=8,
                        decode_tokens_mean=14.0),
        [S.DiurnalRamp(period_s=1800.0, floor=0.3, peak=1.0)], seed=seed)


def _run_hour_virtual():
    import time as _time

    from gie_tpu.storm.engine import EngineConfig, PoolSpec, StormEngine

    eng = StormEngine(
        _hour_program(),
        pool=PoolSpec(n_pods=3),
        cfg=EngineConfig(scrape_interval_s=0.25, world_dt_s=0.05,
                         autoscale_interval_s=2.0),
        virtual_time=True, name="twin-hour")
    try:
        t0 = _time.monotonic()
        res = eng.run()
        wall = _time.monotonic() - t0
    finally:
        eng.close()
    return res.scorecard, wall


def test_virtual_hour_storm_compresses_and_pins_decisions():
    """The gie-twin acceptance core: a >= 1-hour simulated diurnal storm
    completes in well under 60 s of wall clock, error-free, and two
    same-seed runs produce a BIT-IDENTICAL decision sequence (the
    scorecard's decision_fingerprint digests every pick in order plus
    all shed/breaker/rung/autoscale outcomes)."""
    c1, w1 = _run_hour_virtual()
    c2, w2 = _run_hour_virtual()
    assert c1["virtual_time"] is True
    assert c1["duration_s"] == 3600.0
    assert w1 < 60.0 and w2 < 60.0, (w1, w2)
    assert c1["client_5xx"] == 0, c1["client_5xx_detail"]
    assert c1["resets"] == 0 and c1["timeouts"] == 0
    assert c1["ok"] > 400, "the hour-long storm barely served"
    assert c1["final_rung"] == 0
    assert c1["schedule_fingerprint"] == c2["schedule_fingerprint"]
    assert c1["decision_fingerprint"] == c2["decision_fingerprint"], (
        "same-seed virtual runs diverged — the digital twin is not "
        "deterministic")
    for k in ("arrivals", "ok", "shed", "completed", "client_5xx"):
        assert c1[k] == c2[k], (k, c1[k], c2[k])
    SC.validate(c1)


def test_real_vs_virtual_equivalence_on_short_scenario():
    """The equivalence contract (docs/STORM.md "virtual clock"): the
    SAME scenario and seed, run in real time and under virtual_time,
    agree on the schedule fingerprint, every shed count, and the breaker
    open/close EVENT ORDER — and both scorecards carry every
    REQUIRED_FIELDS entry (latency percentiles compared for presence
    only; their values live on different clocks by design)."""
    from gie_tpu.storm.engine import EngineConfig, run_scenario

    real = run_scenario(
        "storm-equivalence",
        cfg=EngineConfig(virtual_time=False)).scorecard
    virt = run_scenario(
        "storm-equivalence",
        cfg=EngineConfig(virtual_time=True)).scorecard
    assert real["virtual_time"] is False
    assert virt["virtual_time"] is True
    assert real["schedule_fingerprint"] == virt["schedule_fingerprint"]
    assert real["seed"] == virt["seed"]
    assert real["shed"] == virt["shed"] == 0
    assert real["shed_by_band"] == virt["shed_by_band"]
    assert real["client_5xx"] == 0 and virt["client_5xx"] == 0
    # The scrape-fault burst drives one full breaker lifecycle, and the
    # EVENT ORDER is identical across clock modes.
    assert real["breaker_events"], (
        "the fault burst never opened a breaker — the equivalence run "
        "is vacuous")
    assert real["breaker_events"] == virt["breaker_events"]
    assert [st for _slot, st, _plane in real["breaker_events"]] == [
        "open", "half_open", "closed"]
    for card in (real, virt):
        SC.validate(card)
        missing = [f for f in SC.REQUIRED_FIELDS if f not in card]
        assert missing == []
        # Presence, not value: the two modes' latency numbers live on
        # different clocks.
        assert card["ttft_p50_s"] is not None
        assert card["serve_latency_p99_ms"] >= 0


def test_longhorizon_compressed_storm_multihour_hysteresis(tmp_path):
    """storm-longhorizon (docs/STORM.md): a 2-hour diurnal x hour-spread
    rolling upgrade x half-hour federation partition with a split-brain
    era flip — multi-hour breaker/ladder/autoscale/federation hysteresis
    exercised end to end, in about a minute of wall clock. The first
    test this repo has ever had that sees a drain deadline measured in
    minutes or a staleness floor measured in hours actually elapse."""
    import time as _time

    from gie_tpu.storm.engine import run_scenario

    t0 = _time.monotonic()
    result = run_scenario("storm-longhorizon", dump_dir=str(tmp_path))
    wall = _time.monotonic() - t0
    card = result.scorecard
    assert card["virtual_time"] is True
    assert card["duration_s"] == 7200.0
    # >80x compression floor. The budget carries headroom for shared-box
    # drift: interleaved A/B runs on the CI box measured 59-66 s for the
    # SAME code depending on the hour — a 60 s bound was flaking on noise
    # while a real engine regression (2x) still trips this one.
    assert wall < 90.0, f"2 h compressed storm took {wall:.1f}s wall"
    assert card["client_5xx"] == 0, card["client_5xx_detail"]
    assert card["resets"] == 0 and card["timeouts"] == 0
    assert card["final_rung"] == 0
    assert card["ok"] > 1000
    # The whole pool was replaced, one pod per 10 simulated minutes.
    assert sum(1 for u in card["upgrades"] if u["step"] == "replace") == 4
    assert not [hp for hp in card["final_endpoints"]
                if hp.startswith("10.77.")]
    # Partition -> local-only within the (2-minute!) staleness floor,
    # heal -> deterministic era convergence over the zombie lineage.
    fed = card["federation"]
    assert any(v for t, v in fed["local_only_trace"] if 3600 < t < 5400)
    assert fed["local_only_trace"][-1][1] == 0, "peer never readmitted"
    assert fed["link"]["era_flips"] >= 1
    assert fed["link"]["era_regressions"] >= 1
    assert fed["link"]["installed_era"] == fed["peer_era"]
    SC.validate(card)


def test_trace_replay_maps_recorded_fields():
    recs = [
        {"ts": 100.0, "trace_id": "aa", "prompt_bytes": 2048,
         "decode_tokens": 32.0, "band": "critical", "model": "adapter-1",
         "tenant": "t0", "v": 1},
        {"ts": 100.5, "trace_id": "bb", "prompt_bytes": 512,
         "decode_tokens": 8.0, "band": "sheddable", "model": "base-model",
         "v": 1},
        {"ts": 101.0, "model": "base-model", "v": 1},  # sparse legacy
        {"junk": True},                                # no ts: skipped
    ]
    shape = S.TraceReplay(records=recs)
    tc = S.TrafficConfig(base_qps=1.0, duration_s=0.5, n_sessions=4)
    sched = S.Program(tc, [shape], seed=3).compile()
    assert [a.t for a in sched.arrivals] == [0.0, 0.5, 1.0]
    a0, a1, a2 = sched.arrivals
    assert (a0.band, a0.lora, a0.tenant) == ("critical", "adapter-1", "t0")
    assert a0.prompt_bytes == 2048 and a0.decode_tokens == 32.0
    assert a1.lora is None and a1.band == "sheddable"
    assert a2.prompt_bytes == 1024 and a2.band == "standard"  # defaults
    assert all(0 <= a.session < 4 for a in sched.arrivals)
    # Duration stretched to cover the replay (never silently truncated).
    assert sched.traffic.duration_s >= 2.0
    # Deterministic: the same dump compiles the same fingerprint.
    assert (S.Program(tc, [shape], seed=3).compile().fingerprint()
            == sched.fingerprint())
    # time_scale stretches inter-arrival spacing.
    slow = S.TraceReplay(records=recs, time_scale=2.0)
    assert S.Program(tc, [slow], seed=3).compile().arrivals[1].t == 1.0
    # Registry + loud errors.
    assert "trace_replay" in S.SHAPE_KINDS
    with pytest.raises(ValueError, match="exactly one"):
        S.TraceReplay()
    with pytest.raises(ValueError, match="no timestamped"):
        S.TraceReplay(records=[{"x": 1}])
    with pytest.raises(ValueError, match="time_scale"):
        S.TraceReplay(records=recs, time_scale=0.0)


def test_trace_replay_replays_a_flight_recorder_dump(tmp_path):
    """The PR-10 follow-on closed end to end: a storm run's flight-
    recorder dump (the artifact storm/chaos runs already write) becomes
    a TraceReplay program whose replay produces a valid scorecard — with
    the recorded prompt/band/adapter mix intact."""
    from gie_tpu import obs
    from gie_tpu.obs.recorder import FlightRecorder, load_records
    from gie_tpu.storm.engine import PoolSpec, StormEngine

    prog = S.Program(
        S.TrafficConfig(base_qps=8.0, duration_s=3.0, n_sessions=8),
        [S.LoraChurn(adapters=3, hot=1, rotate_every_s=2.0, p=0.5)],
        seed=1717)
    eng = StormEngine(prog, pool=PoolSpec(n_pods=3),
                      virtual_time=True, name="rec-source")
    try:
        sched = prog.compile()
        # Warm BEFORE arming the recorder: warmup picks are harness
        # traffic (bare PickRequests, no model/decode identity), not
        # workload — a replay dump must carry the storm's arrivals only.
        eng.warmup(sched)
        obs.install(recorder=FlightRecorder(4096))
        try:
            source = eng.run(schedule=sched, warmup=False)
            dump = obs.RECORDER.export_json()
        finally:
            obs.uninstall()
    finally:
        eng.close()
    n_records = len(load_records(dump))
    assert n_records > 10
    path = tmp_path / "rec-source-flightrec.json"
    path.write_text(dump, encoding="utf-8")

    replay = S.TraceReplay(path=str(path))
    prog2 = S.Program(
        S.TrafficConfig(base_qps=1.0, duration_s=1.0, n_sessions=8),
        [replay], seed=2)
    eng2 = StormEngine(prog2, pool=PoolSpec(n_pods=3),
                       virtual_time=True, name="rec-replay")
    try:
        result = eng2.run()
    finally:
        eng2.close()
    card = result.scorecard
    SC.validate(card)
    assert card["arrivals"] == n_records
    assert card["client_5xx"] == 0, card["client_5xx_detail"]
    assert card["ok"] + card["shed"] == card["arrivals"]
    assert card["ok"] > 10
    # The recorded adapter mix survived the round trip.
    assert card["lora_arrivals"] > 0
    assert source.scorecard["arrivals"] == n_records


# ==========================================================================
# Slow soak: the whole stack in one run
# ==========================================================================


@pytest.mark.slow
def test_storm_soak_full_stack_degrades_and_recovers(tmp_path):
    """storm-soak (docs/STORM.md): diurnal ramp + flash crowd + LoRA
    churn + long-context + rolling upgrade + autoscale + warm-standby
    failover probes, over scrape-latency and device-dispatch chaos —
    ext-proc to replication in ONE run, recovered at the end."""
    from gie_tpu.storm.engine import run_scenario

    result = run_scenario("storm-soak", dump_dir=str(tmp_path))
    card = result.scorecard
    assert card["client_5xx"] == 0, card["client_5xx_detail"]
    assert card["resets"] == 0
    assert card["ok"] > 300
    assert card["final_rung"] == 0
    assert card["max_rung"] >= 1
    assert sum(1 for u in card["upgrades"] if u["step"] == "replace") == 6
    # Warm-standby readiness held THROUGH the storm: every failover
    # probe decoded a live digest, at monotonically advancing epochs.
    checks = card["failover_checks"]
    assert len(checks) >= 5
    assert all(c["ok"] for c in checks), checks
    epochs = [c["epoch"] for c in checks]
    assert epochs == sorted(epochs) and epochs[-1] > epochs[0]
    SC.validate(card)


# ==========================================================================
# gie-fed federation storms (ISSUE 12, docs/FEDERATION.md): the four
# scorecard-pinned properties — regional spillover with CRITICAL
# locality, whole-cluster drain bleed, partition -> local-only within
# one staleness window, split-brain era convergence on heal.
# ==========================================================================


@pytest.fixture(scope="module")
def fed_spill(tmp_path_factory):
    """ONE storm-fed-spill replay (3 local pods + a 3-pod imported peer
    cluster under a 4x regional flash crowd), shared by the spill
    assertions below."""
    from gie_tpu.storm.engine import run_scenario

    faults.uninstall()
    dump_dir = str(tmp_path_factory.mktemp("fedstorm"))
    return run_scenario("storm-fed-spill", dump_dir=dump_dir)


def test_fed_spill_crowd_spills_with_zero_5xx(fed_spill):
    """The regional flash crowd exceeds local capacity and SPILLS onto
    the imported peer endpoints — with not one client-visible 5xx,
    reset, or timeout. One cluster stops being the capacity ceiling."""
    card = fed_spill.scorecard
    fed = card["federation"]
    assert card["client_5xx"] == 0, card["client_5xx_detail"]
    assert card["resets"] == 0 and card["timeouts"] == 0
    assert fed["picks"].get("west", {}).get("total", 0) > 10, fed["picks"]
    assert fed["serves"].get("west", 0) > 10
    assert fed["picks"]["local"]["total"] > fed["picks"]["west"]["total"], (
        "the peer is penalized spill capacity, not the default route")
    SC.validate(card)


def test_fed_spill_critical_never_crosses(fed_spill):
    """Local capacity sufficed for CRITICAL throughout (local candidates
    always existed), so no CRITICAL pick crossed the cluster boundary —
    the band-locality half of the spill policy."""
    fed = fed_spill.scorecard["federation"]
    assert fed["critical_remote_picks"] == 0
    assert fed["picks"]["local"]["bands"].get("critical", 0) > 0, (
        "the storm never offered CRITICAL traffic — vacuous")


def test_fed_spill_link_stayed_fresh(fed_spill):
    fed = fed_spill.scorecard["federation"]
    assert fed["link"]["installs"] > 5
    assert fed["link"]["era_regressions"] == 0
    # The peer never went local-only during a healthy-link storm.
    assert all(v == 0 for _t, v in fed["local_only_trace"][3:]), (
        fed["local_only_trace"])


def test_fed_drain_bleeds_to_peer_with_zero_5xx(tmp_path):
    """Whole-cluster drain: after the flag is raised, NEW picks bleed to
    the peer cluster (every band — locality yields to the drain), local
    in-flight completes, and the client never sees a 5xx."""
    from gie_tpu.storm.engine import run_scenario

    result = run_scenario("storm-fed-drain", dump_dir=str(tmp_path))
    card = result.scorecard
    fed = card["federation"]
    assert card["client_5xx"] == 0, card["client_5xx_detail"]
    assert card["resets"] == 0 and card["timeouts"] == 0
    assert fed["draining"] is True
    drain_t = [e["t"] for e in fed["events"]
               if e["event"] == "cluster_drain"]
    assert len(drain_t) == 1
    # New picks after the drain settles are ALL remote (the settle
    # window covers waves already dispatched at the flag flip).
    late_local = [t for t, c in fed["pick_times"]
                  if c == "local" and t > drain_t[0] + 0.5]
    assert late_local == [], late_local
    assert [t for t, c in fed["pick_times"]
            if c == "west" and t > drain_t[0]], "nothing bled to the peer"
    # Traffic before the drain stayed local (no saturation, no spill).
    assert fed["picks"]["local"]["total"] > 0
    SC.validate(card)


def test_fed_partition_local_only_and_split_brain_convergence(tmp_path):
    """Partition: the peer degrades to LOCAL-ONLY within one staleness
    window (plus observe-tick slack) while local traffic serves with
    zero 5xx; the heal arrives with an era flip and a zombie lineage
    interleaved — the importer converges deterministically on the
    greater era, rejects every zombie frame as an era regression, and
    readmits the peer. One seeded retry guards real-time CPU-contention
    flake (the storm-capacity pattern)."""
    from gie_tpu.storm.engine import run_scenario

    result = run_scenario("storm-fed-partition", dump_dir=str(tmp_path))
    card = result.scorecard
    fed = card["federation"]
    part_t = [e["t"] for e in fed["events"] if e["event"] == "partition"]
    first_lo = next(
        (t for t, v in fed["local_only_trace"] if t >= part_t[0] and v),
        None)
    window = fed["local_only_after_s"]
    if first_lo is None or first_lo - part_t[0] > window + 1.0:
        result = run_scenario("storm-fed-partition", seed=656565,
                              dump_dir=str(tmp_path))
        card = result.scorecard
        fed = card["federation"]
        part_t = [e["t"] for e in fed["events"]
                  if e["event"] == "partition"]
        first_lo = next(
            (t for t, v in fed["local_only_trace"]
             if t >= part_t[0] and v), None)
    # Zero client-visible errors: the partition cost cross-cluster
    # capacity, never availability.
    assert card["client_5xx"] == 0, card["client_5xx_detail"]
    assert card["resets"] == 0 and card["timeouts"] == 0
    # Fresh before the partition...
    assert any(v == 0 for t, v in fed["local_only_trace"]
               if t < part_t[0])
    # ...local-only within one staleness window (+ observe-tick slack)...
    assert first_lo is not None, fed["local_only_trace"]
    assert first_lo - part_t[0] <= window + 1.0, (first_lo, part_t)
    # ...and readmitted after the heal.
    heal_t = [e["t"] for e in fed["events"] if e["event"] == "heal"][0]
    assert fed["local_only_trace"][-1][1] == 0
    # Split-brain convergence: the installed era ratcheted to the peer's
    # NEW (greater) era, and the zombie's frames all rejected.
    assert fed["link"]["installed_era"] == fed["peer_era"]
    assert fed["link"]["era_flips"] >= 1
    assert fed["link"]["era_regressions"] >= 1
    assert heal_t > part_t[0]
    SC.validate(card)


def test_fed_scenarios_ship_and_compile_deterministically():
    from gie_tpu.resilience import scenarios
    from gie_tpu.storm.engine import FederationSpec

    names = scenarios.list_scenarios()
    assert {"storm-fed-spill", "storm-fed-drain",
            "storm-fed-partition"} <= set(names)
    scn = scenarios.load("storm-fed-partition")
    prog = S.program_from_drive(scn.drive["storm"], seed=scn.seed)
    a, b = prog.compile(), prog.compile()
    assert a.fingerprint() == b.fingerprint()
    kinds = {e.kind for e in a.events}
    assert kinds == {"peer_partition", "peer_heal"}
    # The drive's federation block maps onto FederationSpec exactly.
    FederationSpec(**scn.drive["storm"]["federation"])


# ==========================================================================
# gie-fleet fleet-scale storm (ISSUE 18, docs/FLEET.md): 16 simulated
# clusters under the hierarchical FleetPicker — goodput parity with the
# flat dense scheduler (covering top-K => identical decision
# fingerprint), zero CRITICAL-band mis-spills, coarse-stage provenance.
# ==========================================================================


@pytest.fixture(scope="module")
def fleet_storm(tmp_path_factory):
    """ONE storm-fleet replay (3 local pods + 15 two-pod peer clusters
    on the virtual clock, FleetPicker armed) plus the SAME storm re-run
    with the flat dense scheduler (fleet knobs stripped from the drive)
    — the goodput-parity baseline."""
    from gie_tpu.resilience import scenarios
    from gie_tpu.storm.engine import engine_from_drive, run_scenario

    faults.uninstall()
    dump_dir = str(tmp_path_factory.mktemp("fleetstorm"))
    fleet = run_scenario("storm-fleet", dump_dir=dump_dir)
    scn = scenarios.load("storm-fleet")
    dense_drive = dict(scn.drive["storm"])
    dense_drive.pop("fleet_topk")
    dense_drive.pop("fleet_cell_cap", None)
    eng = engine_from_drive(dense_drive, seed=scn.seed,
                            name="storm-fleet-dense")
    try:
        dense = eng.run()
    finally:
        eng.close()
    return fleet.scorecard, dense.scorecard


def test_fleet_storm_16_clusters_no_critical_misspill(fleet_storm):
    """16 simulated clusters (local + 15 imported peers): the crowd
    spills onto the fleet with zero client-visible errors, and not one
    CRITICAL pick crosses a cluster boundary while local candidates
    exist — the mis-spill half of the fleet acceptance."""
    card, _dense = fleet_storm
    fed = card["federation"]
    assert len(fed["peers"]) == 15, fed["peers"]  # + local = 16 clusters
    assert card["client_5xx"] == 0, card["client_5xx_detail"]
    assert card["resets"] == 0 and card["timeouts"] == 0
    remote = sum(per["total"] for cluster, per in fed["picks"].items()
                 if cluster != "local")
    assert remote > 0, fed["picks"]
    assert fed["critical_remote_picks"] == 0
    assert fed["picks"]["local"]["bands"].get("critical", 0) > 0, (
        "the storm never offered CRITICAL traffic — vacuous")
    SC.validate(card)


def test_fleet_storm_goodput_parity_with_dense_baseline(fleet_storm):
    """Covering top-K (K * cell_cap >= M): the hierarchical pick cycle
    is BITWISE the dense cycle (docs/FLEET.md parity contract), so the
    whole virtual storm — every pick, shed, and breaker outcome — lands
    on the IDENTICAL decision fingerprint as the flat scheduler."""
    card, dense = fleet_storm
    assert card["virtual_time"] is True and dense["virtual_time"] is True
    assert "fleet" in card and "fleet" not in dense
    assert card["schedule_fingerprint"] == dense["schedule_fingerprint"]
    assert card["decision_fingerprint"] == dense["decision_fingerprint"], (
        "the hierarchical picker changed a decision the covering-K "
        "parity contract pins")
    for k in ("arrivals", "ok", "shed", "completed", "client_5xx"):
        assert card[k] == dense[k], (k, card[k], dense[k])


def test_fleet_storm_scorecard_provenance(fleet_storm):
    """The scorecard's fleet section records the coarse stage: exact
    mode at this M, covering compression, and every landed pick's cell
    inside its request's candidate list (no -1 ranks at covering K)."""
    card, _dense = fleet_storm
    fleet = card["fleet"]
    assert fleet["mode"] == "exact"
    assert fleet["topk"] == 2 and fleet["cell_cap"] == 32
    assert fleet["compression_ratio"] == 1.0  # covering K at this M
    assert fleet["waves"] > 0
    hist = fleet["topk_hit_histogram"]
    assert sum(hist.values()) > 0
    assert hist.get("-1", 0) == 0, hist
    assert sum(e["picks"] for e in fleet["hot_cells"]) > 0


def test_cluster_drain_and_partition_shapes():
    drain = S.ClusterDrain(at_s=2.0)
    assert [e.kind for e in drain.control_events(5.0)] == ["cluster_drain"]
    assert drain.control_events(1.0) == []
    part = S.PeerPartition(at_s=1.0, heal_s=3.0, flip_era=False)
    evs = part.control_events(10.0)
    assert [(e.kind, e.args) for e in evs] == [
        ("peer_partition", ()), ("peer_heal", (0,))]
    with pytest.raises(ValueError):
        S.PeerPartition(at_s=3.0, heal_s=1.0)


# ==========================================================================
# gie-wire (ISSUE 16): multi-core ext-proc admission model
# ==========================================================================


def _crowd_admission_card(workers: int, seed: int = 909):
    """A flash crowd through the multi-core admission gate on the
    virtual clock. Sized so ONE worker's admission capacity
    (1/extproc_admission_s = ~33 req/s) is well under the crowd's
    offered rate (~90 req/s) while FOUR workers clear it — the client
    concurrency cap then converts a saturated acceptor into skipped
    offers exactly the way a finite client pool does. queue_limit is
    opened up so the scheduler never sheds: every throughput difference
    in the sweep is the acceptor pool's, not the TPU cycle's."""
    from gie_tpu.storm.engine import EngineConfig, PoolSpec, StormEngine

    prog = S.Program(
        S.TrafficConfig(base_qps=30.0, duration_s=6.0, n_sessions=8,
                        decode_tokens_mean=10.0),
        [S.FlashCrowd(at_s=1.0, ramp_s=0.5, hold_s=2.0, magnitude=3.0)],
        seed=seed)
    eng = StormEngine(
        prog, pool=PoolSpec(n_pods=6),
        cfg=EngineConfig(
            extproc_workers=workers, extproc_admission_s=0.03,
            max_concurrency=64, queue_limit=512.0, kv_limit=0.999,
            scrape_interval_s=0.1, world_dt_s=0.05,
            autoscale_interval_s=2.0),
        virtual_time=True, name=f"wire-admission-w{workers}")
    try:
        return eng.run().scorecard
    finally:
        eng.close()


@pytest.fixture(scope="module")
def admission_sweep():
    return {w: _crowd_admission_card(w) for w in (1, 2, 4)}


def test_admission_throughput_monotone_through_workers(admission_sweep):
    """The gie-wire storm acceptance: the same seeded flash crowd at
    workers 1/2/4 — admitted-request throughput is monotone through 4
    workers (the saturated single acceptor skips offers at the client
    cap; four clear the crowd), with zero client-visible 5xx at every
    width."""
    cards = admission_sweep
    admitted = {w: cards[w]["extproc"]["admitted"] for w in (1, 2, 4)}
    served = {w: cards[w]["ok"] for w in (1, 2, 4)}
    assert admitted[1] <= admitted[2] <= admitted[4], admitted
    assert admitted[1] < admitted[4], (
        f"the sweep is vacuous — one worker admitted everything "
        f"({admitted}); the crowd never saturated admission")
    assert served[1] <= served[2] <= served[4], served
    for w, card in cards.items():
        assert card["client_5xx"] == 0, (w, card["client_5xx_detail"])
        assert card["resets"] == 0 and card["timeouts"] == 0, w
        assert card["shed"] == 0, (
            f"workers={w}: the scheduler shed — the sweep no longer "
            f"isolates the acceptor pool")
        # Every admitted stream reached the real ext-proc server.
        assert card["extproc"]["admitted"] == (
            card["arrivals"] - card["client_skipped"]), w
    # Saturation shows up as admission queueing on the narrow pool.
    assert (cards[1]["extproc"]["admission_wait_p99_ms"]
            > cards[4]["extproc"]["admission_wait_p99_ms"]), cards[1]


def test_admission_accepts_balanced_across_workers(admission_sweep):
    """No one-worker skew: the connection-pool round robin spreads
    accepts within one stream of each other at every width, and the
    per-worker busy seconds follow the same spread."""
    for w, card in admission_sweep.items():
        sec = card["extproc"]
        accepts = sec["per_worker_accepts"]
        assert len(accepts) == w == sec["workers"]
        assert sum(accepts) == sec["admitted"]
        assert max(accepts) - min(accepts) <= 1, (w, accepts)
        assert sec["per_worker_busy_s"] == [
            round(a * sec["admission_service_s"], 3) for a in accepts]


def test_admission_model_is_deterministic_and_fingerprinted():
    """Two same-seed virtual runs of the gated storm agree bit-for-bit
    — and the gate's accept spread is PART of the digest (a skewed
    replay would change the fingerprint), while an ungated storm's
    scorecard carries no extproc section at all (the pre-wire pinned
    fingerprints stay byte-identical)."""
    a = _crowd_admission_card(2)
    b = _crowd_admission_card(2)
    assert a["decision_fingerprint"] == b["decision_fingerprint"]
    assert a["extproc"] == b["extproc"]
    for k in ("arrivals", "ok", "shed", "completed", "client_5xx",
              "client_skipped"):
        assert a[k] == b[k], (k, a[k], b[k])
    SC.validate(a)


def test_admission_drive_keys_round_trip():
    """extproc_workers / extproc_admission_s are whitelisted drive.storm
    knobs: engine_from_drive arms the gate, and a typo still fails
    loudly (the silent-default replay hazard)."""
    from gie_tpu.storm.engine import engine_from_drive

    drive = {"base_qps": 5.0, "duration_s": 2.0, "virtual_time": True,
             "extproc_workers": 3, "extproc_admission_s": 0.02}
    eng = engine_from_drive(drive, seed=4, name="wire-drive")
    try:
        assert eng.cfg.extproc_workers == 3
        assert eng.cfg.extproc_admission_s == 0.02
        assert eng._admission is not None
        assert eng._admission.workers == 3
    finally:
        eng.close()
    with pytest.raises(ValueError, match="extproc_worker_count"):
        engine_from_drive({"extproc_worker_count": 2}, seed=4)
