"""Execute the COMMITTED CRD CEL rules against fixture objects.

Mirror of reference test/cel/inferencepool_test.go:31-136, which creates
real objects against a real apiserver running the generated CRDs. Here the
actual `x-kubernetes-validations` rule STRINGS from config/crd/bases/*.yaml
are parsed and evaluated by gie_tpu/api/cel.py — a typo in a committed rule
now fails these tests instead of shipping, and the Python validate()
mirrors are drift-guarded against the executed YAML verdicts.
"""

import copy
import os

import pytest
import yaml

from gie_tpu.api import types as api
from gie_tpu.api.cel import (
    CelError,
    apply_defaults,
    compile_rule,
    crd_schema,
    evaluate_rule,
    validate_against_schema,
)

CRD_PATH = os.path.join(
    os.path.dirname(os.path.dirname(__file__)),
    "config", "crd", "bases",
    "inference.networking.k8s.io_inferencepools.yaml",
)


@pytest.fixture(scope="module")
def schema():
    with open(CRD_PATH) as f:
        crd = yaml.safe_load(f)
    return crd_schema(crd)


def base_pool_dict():
    """The reference's baseInferencePool (inferencepool_test.go:34-54)."""
    return {
        "apiVersion": f"{api.GROUP}/v1",
        "kind": "InferencePool",
        "metadata": {"name": "base-pool", "namespace": "default"},
        "spec": {
            "targetPorts": [{"number": 8000}],
            "selector": {"matchLabels": {"app": "model-server"}},
            "endpointPickerRef": {
                "name": "epp",
                "kind": "Service",
                "port": {"number": 9002},
            },
        },
    }


def admit(schema, obj):
    """What the apiserver does: default, then run every committed rule."""
    return validate_against_schema(schema, apply_defaults(schema, obj))


# ---- the reference's table (executed against the committed YAML) ----------


def test_valid_configuration_admitted(schema):
    assert admit(schema, base_pool_dict()) == []


def test_app_protocol_admitted(schema):
    obj = base_pool_dict()
    obj["spec"]["appProtocol"] = "kubernetes.io/h2c"
    assert admit(schema, obj) == []


def test_kind_unset_defaults_to_service_port_required(schema):
    obj = base_pool_dict()
    del obj["spec"]["endpointPickerRef"]["kind"]  # apiserver defaults it
    del obj["spec"]["endpointPickerRef"]["port"]
    failures = admit(schema, obj)
    assert any("port is required" in f for f in failures)


def test_kind_service_port_required(schema):
    obj = base_pool_dict()
    del obj["spec"]["endpointPickerRef"]["port"]
    failures = admit(schema, obj)
    assert any("port is required" in f for f in failures)


def test_non_service_kind_admits_portless_ref(schema):
    obj = base_pool_dict()
    obj["spec"]["endpointPickerRef"]["kind"] = "EndpointPicker"
    del obj["spec"]["endpointPickerRef"]["port"]
    assert admit(schema, obj) == []


def test_unique_ports_admitted(schema):
    obj = base_pool_dict()
    obj["spec"]["targetPorts"] = [
        {"number": n} for n in (8000, 80, 8081, 443)
    ]
    assert admit(schema, obj) == []


def test_duplicate_ports_rejected(schema):
    obj = base_pool_dict()
    obj["spec"]["targetPorts"] = [
        {"number": n} for n in (8000, 80, 8000, 443)
    ]
    failures = admit(schema, obj)
    assert any("port number must be unique" in f for f in failures)


# ---- drift guards ---------------------------------------------------------


def test_committed_rules_drift_guard(schema):
    """The executed YAML verdict must agree with the Python validate()
    mirror on every scenario above — edits to either side that change
    semantics fail here."""
    scenarios = []
    obj = base_pool_dict()
    scenarios.append((obj, True))
    dup = copy.deepcopy(obj)
    dup["spec"]["targetPorts"] = [{"number": 80}, {"number": 80}]
    scenarios.append((dup, False))
    portless = copy.deepcopy(obj)
    del portless["spec"]["endpointPickerRef"]["port"]
    scenarios.append((portless, False))
    portless_ok = copy.deepcopy(portless)
    portless_ok["spec"]["endpointPickerRef"]["kind"] = "EndpointPicker"
    scenarios.append((portless_ok, True))

    for manifest, want_ok in scenarios:
        cel_ok = admit(schema, manifest) == []
        pool = api.pool_from_dict(manifest)
        try:
            pool.validate()
            py_ok = True
        except api.ValidationError:
            py_ok = False
        assert cel_ok == py_ok == want_ok, (
            f"CEL={cel_ok} python={py_ok} want={want_ok}: {manifest}")


def test_nonsense_rule_edit_is_caught(schema):
    """If a committed rule string is edited to nonsense, evaluation must
    surface it (rule error -> rejection), never silently admit."""
    broken = copy.deepcopy(schema)
    tp = broken["properties"]["spec"]["properties"]["targetPorts"]
    tp["x-kubernetes-validations"][0]["rule"] = (
        "self.all(p1, self.exists_one(")  # truncated mid-expression
    failures = validate_against_schema(
        broken, apply_defaults(broken, base_pool_dict()))
    assert any("rule error" in f for f in failures)

    broken2 = copy.deepcopy(schema)
    tp2 = broken2["properties"]["spec"]["properties"]["targetPorts"]
    tp2["x-kubernetes-validations"][0]["rule"] = (
        "self.all(p1, p1.nunber > 0)")  # typo'd field name
    failures2 = validate_against_schema(
        broken2, apply_defaults(broken2, base_pool_dict()))
    assert any("rule error" in f for f in failures2)


# ---- evaluator semantics (the CEL subset itself) --------------------------


def test_cel_semantics():
    assert evaluate_rule("self == 3", 3) is True
    assert evaluate_rule("self != 3", 3) is False
    assert evaluate_rule("self.all(x, x > 0)", [1, 2, 3]) is True
    assert evaluate_rule("self.all(x, x > 0)", [1, -2]) is False
    assert evaluate_rule("self.exists_one(x, x == 2)", [1, 2, 3]) is True
    assert evaluate_rule("self.exists_one(x, x == 2)", [2, 2]) is False
    assert evaluate_rule("has(self.a)", {"a": 1}) is True
    assert evaluate_rule("has(self.a)", {"b": 1}) is False
    assert evaluate_rule("size(self) <= 2", [1, 2]) is True
    assert evaluate_rule("self.startsWith('ab')", "abc") is True
    assert evaluate_rule("'x' in self", ["x", "y"]) is True
    assert evaluate_rule("!(self > 2) || self == 9", 9) is True
    # CEL's commutative boolean error absorption.
    assert evaluate_rule("self.kind != 'Service' || has(self.port)",
                         {"kind": "Other"}) is True
    with pytest.raises(CelError):
        evaluate_rule("self.missing == 1", {"present": 1})
    with pytest.raises(CelError):
        evaluate_rule("self ==", 1)
    # Runtime type errors and malformed regexes are rule errors (CelError),
    # never raw Python exceptions leaking through admit().
    with pytest.raises(CelError):
        evaluate_rule("self < 'a'", 1)
    with pytest.raises(CelError):
        evaluate_rule("self.matches('[')", "abc")
    # Compile once, evaluate many (the walker's hot path).
    fn = compile_rule("self.all(p1, self.exists_one(p2, p1.number==p2.number))")
    assert fn([{"number": 1}, {"number": 2}]) is True
    assert fn([{"number": 1}, {"number": 1}]) is False
