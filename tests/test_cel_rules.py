"""Execute the COMMITTED CRD CEL rules against fixture objects.

Mirror of reference test/cel/inferencepool_test.go:31-136, which creates
real objects against a real apiserver running the generated CRDs. Here the
actual `x-kubernetes-validations` rule STRINGS from config/crd/bases/*.yaml
are parsed and evaluated by gie_tpu/api/cel.py — a typo in a committed rule
now fails these tests instead of shipping, and the Python validate()
mirrors are drift-guarded against the executed YAML verdicts.
"""

import copy
import os

import pytest
import yaml

from gie_tpu.api import types as api
from gie_tpu.api.cel import (
    CelError,
    apply_defaults,
    compile_rule,
    crd_schema,
    evaluate_rule,
    validate_against_schema,
)

CRD_PATH = os.path.join(
    os.path.dirname(os.path.dirname(__file__)),
    "config", "crd", "bases",
    "inference.networking.k8s.io_inferencepools.yaml",
)


@pytest.fixture(scope="module")
def schema():
    with open(CRD_PATH) as f:
        crd = yaml.safe_load(f)
    return crd_schema(crd)


def base_pool_dict():
    """The reference's baseInferencePool (inferencepool_test.go:34-54)."""
    return {
        "apiVersion": f"{api.GROUP}/v1",
        "kind": "InferencePool",
        "metadata": {"name": "base-pool", "namespace": "default"},
        "spec": {
            "targetPorts": [{"number": 8000}],
            "selector": {"matchLabels": {"app": "model-server"}},
            "endpointPickerRef": {
                "name": "epp",
                "kind": "Service",
                "port": {"number": 9002},
            },
        },
    }


def admit(schema, obj):
    """What the apiserver does: default, then run every committed rule."""
    return validate_against_schema(schema, apply_defaults(schema, obj))


# ---- the reference's table (executed against the committed YAML) ----------


def test_valid_configuration_admitted(schema):
    assert admit(schema, base_pool_dict()) == []


def test_app_protocol_admitted(schema):
    obj = base_pool_dict()
    obj["spec"]["appProtocol"] = "kubernetes.io/h2c"
    assert admit(schema, obj) == []


def test_kind_unset_defaults_to_service_port_required(schema):
    obj = base_pool_dict()
    del obj["spec"]["endpointPickerRef"]["kind"]  # apiserver defaults it
    del obj["spec"]["endpointPickerRef"]["port"]
    failures = admit(schema, obj)
    assert any("port is required" in f for f in failures)


def test_kind_service_port_required(schema):
    obj = base_pool_dict()
    del obj["spec"]["endpointPickerRef"]["port"]
    failures = admit(schema, obj)
    assert any("port is required" in f for f in failures)


def test_non_service_kind_admits_portless_ref(schema):
    obj = base_pool_dict()
    obj["spec"]["endpointPickerRef"]["kind"] = "EndpointPicker"
    del obj["spec"]["endpointPickerRef"]["port"]
    assert admit(schema, obj) == []


def test_unique_ports_admitted(schema):
    obj = base_pool_dict()
    obj["spec"]["targetPorts"] = [
        {"number": n} for n in (8000, 80, 8081, 443)
    ]
    assert admit(schema, obj) == []


def test_duplicate_ports_rejected(schema):
    obj = base_pool_dict()
    obj["spec"]["targetPorts"] = [
        {"number": n} for n in (8000, 80, 8000, 443)
    ]
    failures = admit(schema, obj)
    assert any("port number must be unique" in f for f in failures)


# ---- drift guards ---------------------------------------------------------


def test_committed_rules_drift_guard(schema):
    """The executed YAML verdict must agree with the Python validate()
    mirror on every scenario above — edits to either side that change
    semantics fail here."""
    scenarios = []
    obj = base_pool_dict()
    scenarios.append((obj, True))
    dup = copy.deepcopy(obj)
    dup["spec"]["targetPorts"] = [{"number": 80}, {"number": 80}]
    scenarios.append((dup, False))
    portless = copy.deepcopy(obj)
    del portless["spec"]["endpointPickerRef"]["port"]
    scenarios.append((portless, False))
    portless_ok = copy.deepcopy(portless)
    portless_ok["spec"]["endpointPickerRef"]["kind"] = "EndpointPicker"
    scenarios.append((portless_ok, True))

    for manifest, want_ok in scenarios:
        cel_ok = admit(schema, manifest) == []
        pool = api.pool_from_dict(manifest)
        try:
            pool.validate()
            py_ok = True
        except api.ValidationError:
            py_ok = False
        assert cel_ok == py_ok == want_ok, (
            f"CEL={cel_ok} python={py_ok} want={want_ok}: {manifest}")


def test_nonsense_rule_edit_is_caught(schema):
    """If a committed rule string is edited to nonsense, evaluation must
    surface it (rule error -> rejection), never silently admit."""
    broken = copy.deepcopy(schema)
    tp = broken["properties"]["spec"]["properties"]["targetPorts"]
    tp["x-kubernetes-validations"][0]["rule"] = (
        "self.all(p1, self.exists_one(")  # truncated mid-expression
    failures = validate_against_schema(
        broken, apply_defaults(broken, base_pool_dict()))
    assert any("rule error" in f for f in failures)

    broken2 = copy.deepcopy(schema)
    tp2 = broken2["properties"]["spec"]["properties"]["targetPorts"]
    tp2["x-kubernetes-validations"][0]["rule"] = (
        "self.all(p1, p1.nunber > 0)")  # typo'd field name
    failures2 = validate_against_schema(
        broken2, apply_defaults(broken2, base_pool_dict()))
    assert any("rule error" in f for f in failures2)


# ---- evaluator semantics (the CEL subset itself) --------------------------


def test_cel_semantics():
    assert evaluate_rule("self == 3", 3) is True
    assert evaluate_rule("self != 3", 3) is False
    assert evaluate_rule("self.all(x, x > 0)", [1, 2, 3]) is True
    assert evaluate_rule("self.all(x, x > 0)", [1, -2]) is False
    assert evaluate_rule("self.exists_one(x, x == 2)", [1, 2, 3]) is True
    assert evaluate_rule("self.exists_one(x, x == 2)", [2, 2]) is False
    assert evaluate_rule("has(self.a)", {"a": 1}) is True
    assert evaluate_rule("has(self.a)", {"b": 1}) is False
    assert evaluate_rule("size(self) <= 2", [1, 2]) is True
    assert evaluate_rule("self.startsWith('ab')", "abc") is True
    assert evaluate_rule("'x' in self", ["x", "y"]) is True
    assert evaluate_rule("!(self > 2) || self == 9", 9) is True
    # CEL's commutative boolean error absorption.
    assert evaluate_rule("self.kind != 'Service' || has(self.port)",
                         {"kind": "Other"}) is True
    with pytest.raises(CelError):
        evaluate_rule("self.missing == 1", {"present": 1})
    with pytest.raises(CelError):
        evaluate_rule("self ==", 1)
    # Runtime type errors and malformed regexes are rule errors (CelError),
    # never raw Python exceptions leaking through admit().
    with pytest.raises(CelError):
        evaluate_rule("self < 'a'", 1)
    with pytest.raises(CelError):
        evaluate_rule("self.matches('[')", "abc")
    # Compile once, evaluate many (the walker's hot path).
    fn = compile_rule("self.all(p1, self.exists_one(p2, p1.number==p2.number))")
    assert fn([{"number": 1}, {"number": 2}]) is True
    assert fn([{"number": 1}, {"number": 1}]) is False


# --------------------------------------------------------------------- #
# cel-spec conformance vectors (VERDICT r02 #5)
# --------------------------------------------------------------------- #
# Transcribed from the cel-spec conformance simple-test suites
# (github.com/google/cel-spec tests/simple/testdata: basic.json,
# comparisons.json, logic.json, macros.json, string.json) — the subset
# this evaluator claims. Each vector is (expression, environment-less
# expected value); `self` is unused so the rules run with a dummy binding.

SPEC_VECTORS_TRUE = [
    # basic / literals
    "true",
    "1 == 1",
    "42 == 42",
    "3.14 == 3.14",
    "'hello' == 'hello'",
    "null == null",
    "[] == []",
    "[1, 2] == [1, 2]",
    # comparisons: int
    "1 < 2", "2 <= 2", "3 > 2", "3 >= 3", "1 != 2",
    # comparisons: double
    "1.0 < 1.5", "2.5 > 2.0",
    # comparisons: string (lexicographic, code-point order)
    "'a' < 'b'", "'abc' < 'abd'", "'' < 'a'",
    # arithmetic (+ - only; * / % are outside the subset)
    "1 + 2 == 3", "5 - 3 == 2", "-5 + 10 == 5",
    "'ab' + 'cd' == 'abcd'",
    "[1] + [2] == [1, 2]",
    # logic: short-circuit and commutative error absorption
    "true || false",
    "!false",
    "false || true",
    "true && true",
    "!(true && false)",
    # size() on strings counts code points; on lists, elements
    "size('') == 0",
    "size('four') == 4",
    "size([1, 2, 3]) == 3",
    # membership
    "1 in [1, 2]",
    "!(3 in [1, 2])",
    # string methods
    "'hello'.contains('ell')",
    "'hello'.startsWith('he')",
    "'hello'.endsWith('lo')",
    "'hello'.matches('^h.*o$')",
    "'hello'.size() == 5",
    # macros over list literals
    "[1, 2, 3].all(x, x > 0)",
    "![0, 1].all(x, x > 0)",
    "[1, 2, 3].exists(x, x == 2)",
    "![1, 2].exists(x, x == 9)",
    "[1, 2, 3].exists_one(x, x == 2)",
    "![2, 2].exists_one(x, x == 2)",
    "[1, 2, 3].filter(x, x > 1) == [2, 3]",
    "[1, 2].map(x, x + 1) == [2, 3]",
]


@pytest.mark.parametrize("expr", SPEC_VECTORS_TRUE)
def test_cel_spec_vector(expr):
    assert evaluate_rule(expr, None) is True, expr


def test_cel_spec_error_absorption():
    """cel-spec logic.json: && and || are commutative — a determinate
    answer on either side absorbs the other side's error; two errors
    stay an error."""
    err = "boom.missing"  # undeclared variable -> evaluation error
    assert evaluate_rule(f"true || {err}", None) is True
    assert evaluate_rule(f"{err} || true", None) is True
    assert evaluate_rule(f"false && {err}", None) is False
    assert evaluate_rule(f"{err} && false", None) is False
    with pytest.raises(CelError):
        evaluate_rule(f"false || {err}", None)
    with pytest.raises(CelError):
        evaluate_rule(f"true && {err}", None)
    with pytest.raises(CelError):
        evaluate_rule(f"{err} || {err}", None)


def test_cel_spec_unicode_size():
    """CEL size(string) counts Unicode code points, not bytes."""
    assert evaluate_rule("size(self) == 3", "ééé") is True
    assert evaluate_rule("self.size() == 1", "\U0001f600") is True


def test_cel_heterogeneous_equality():
    """cel-spec: equality across types is false (never an error) for
    distinct types; numeric 1 == 1.0 compares by value."""
    assert evaluate_rule("1 == 1.0", None) is True
    assert evaluate_rule("self == 'x'", 1) is False
    assert evaluate_rule("self != 'x'", 1) is True


# --------------------------------------------------------------------- #
# Unsupported-feature rejection at crdgen time (VERDICT r02 #5)
# --------------------------------------------------------------------- #

from gie_tpu.api.cel import UnsupportedCel, validate_rule_support  # noqa: E402


def test_committed_rules_pass_support_gate():
    """Every rule in both committed CRDs is inside the supported subset."""
    from gie_tpu.api.cel import iter_rules
    from gie_tpu.api.crdgen import inferencepool_crd, inferencepoolimport_crd

    n = 0
    for crd in (inferencepool_crd(), inferencepoolimport_crd()):
        for rule in iter_rules(crd):
            validate_rule_support(rule)
            n += 1
    assert n >= 2  # targetPorts uniqueness + port-required-when-Service


@pytest.mark.parametrize(
    "rule",
    [
        "int(self) == 1",          # type conversion function
        "self.map(x, x).min() == 1",  # unknown method
        "duration(self) < duration('1s')",
        "self.orValue(1) == 1",
        "self.matches('(?=lookahead)')",   # RE2-incompatible regex
        "self.matches('(a)\\\\1')",        # backreference
        "self.matches('(?P<a>x)(?P=a)')",  # named backreference
        "self.matches('(?(1)a|b)')",       # conditional group
        "self == '\\n'",                   # escape the lexer strips
    ],
)
def test_unsupported_feature_rejected(rule):
    with pytest.raises(CelError):
        validate_rule_support(rule)


@pytest.mark.parametrize(
    "rule",
    [
        "self ? 1 : 2",    # ternary
        "self * 2 == 4",   # multiplication
        "self % 2 == 0",   # modulo
        "self / 2 == 1",   # division
        "1u == 1u",        # uint literal
        "b'x' == b'x'",    # bytes literal
    ],
)
def test_unsupported_syntax_rejected_by_parser(rule):
    with pytest.raises(CelError):
        validate_rule_support(rule)


def test_crdgen_refuses_unsupported_rule(tmp_path, monkeypatch):
    """generate() fails the build when a CRD carries a rule outside the
    subset — it must never ship YAML it cannot evaluate faithfully."""
    from gie_tpu.api import crdgen

    broken = crdgen.inferencepool_crd()
    broken["spec"]["versions"][0]["schema"]["openAPIV3Schema"].setdefault(
        "x-kubernetes-validations", []
    ).append({"rule": "duration(self.x) < duration('1s')", "message": "no"})
    monkeypatch.setattr(crdgen, "inferencepool_crd", lambda: broken)
    with pytest.raises(ValueError, match="supported CEL subset"):
        crdgen.generate(str(tmp_path))
