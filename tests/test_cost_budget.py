"""HBM-traffic budget regression gate for the compiled scheduling cycle.

The <=50 us pick-latency target (BASELINE.md) is an HBM-bandwidth budget
in disguise: one v5e moves ~819 GB/s, so the 1024x256 cycle must stay
within ~40 MB of bytes accessed. Round 4 cut the cycle from 51.4 MB to
~30 MB (fused prefix sweep + chunk-axis bucketing — docs/BENCH_NOTES.md
round 4); this test pins the ceiling so a future change that reintroduces
a materialized [N, C, W, 32] unpack or an unbucketed axis fails loudly
instead of silently costing 2x on hardware.

The measurement recipe lives in gie_tpu/utils/costmodel.py and is shared
with hack/cost_analysis.py (the ceilings were calibrated against that
exact fixture); cycle_cost raises if the backend stops reporting the
metric, so the gate can never pass vacuously. Ceilings carry ~15% slack
over measured values so legitimate small changes don't thrash the gate;
a floor guards against the measurement collapsing to nonsense.
"""

import pytest

from gie_tpu.sched.profile import ProfileConfig
from gie_tpu.utils.costmodel import cycle_cost


@pytest.mark.parametrize("name,cfg,ceiling_mb", [
    # Re-baselined 2026-08 (PR 6): measured 35.0 MB / 50.2 Mflop on this
    # container's jaxlib 0.4.36 CPU pipeline. Attribution (worktree
    # sweep with hack/cost_analysis.py at the seed and every PR 1-5
    # commit): bytes AND flops are bit-identical at all six points, so
    # the 27.5 -> 35.0 MB step is the XLA version's fusion/accounting,
    # not a code regression — the math never changed. Per-feature split
    # on this backend: prefix sweep 4.5 MB, session affinity 5.4 MB,
    # LoRA 1.6 MB; cost analysis charges 18.6 MB to state-operand
    # traffic and 11.1 MB to outputs. Ceiling = measured + ~15% slack,
    # same rule as the original calibration. If a future jaxlib drops
    # the measurement back to ~27 MB, tighten this again.
    ("default-topk", ProfileConfig(), 40.0),
    # Re-baselined 2026-08 (PR 15, gie-mesh): measured 63.2 MB, up from
    # 55.5 — the layout-invariant grouped reductions (sinkhorn.py: fixed
    # 8-group partials + ordered fold per sweep, the price of bit-equal
    # picks across every dp x tp mesh layout) materialize the 4-D kernel
    # view and per-iteration group partials the fused matvecs never
    # wrote. Ceiling = measured + ~15% slack, same rule as the others.
    ("sinkhorn", ProfileConfig(picker="sinkhorn"), 72.0),
])
def test_cycle_hbm_budget(name, cfg, ceiling_mb):
    got_mb = cycle_cost(cfg)["bytes"] / 1e6
    assert got_mb >= 5.0, (
        f"{name} cycle reports only {got_mb:.1f} MB — the cost analysis "
        "is no longer measuring the real program")
    assert got_mb <= ceiling_mb, (
        f"{name} cycle now accesses {got_mb:.1f} MB (> {ceiling_mb} MB "
        f"ceiling) — a shape/fusion regression that will show up as "
        f"pick latency on hardware; run hack/cost_analysis.py to bisect")
