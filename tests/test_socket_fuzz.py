"""Adversarial fuzz against the LIVE ext-proc gRPC socket.

VERDICT r02 #7: the in-memory fuzz (test_protocol_fuzz.py) exercises the
handler loop but not the transport. Here a real grpc.server is driven over
TCP with identity (bytes) serializers — exactly the frames a real Envoy
puts on the wire — with Envoy-shaped malformed inputs: truncated frames,
unknown fields, out-of-order phases, random blobs, and mid-stream
disconnects during deferred-header picks. After every abuse the SAME
server must still serve a well-formed stream.

Reference: docs/proposals/004-endpoint-picker-protocol/README.md (protocol
contract); pkg/lwepp/handlers/server.go:105-287 (the loop being abused).
"""

import random
import threading
import time
from concurrent import futures

import grpc
import pytest

from gie_tpu.extproc import RoundRobinPicker, StreamingServer, pb
from gie_tpu.extproc.service import SERVICE_NAME, add_extproc_service

from tests.test_extproc import dest_header, headers_msg, make_ds

_identity = lambda b: b  # noqa: E731 — raw bytes on the wire


@pytest.fixture(scope="module")
def live():
    """One real server + raw-bytes channel shared by every fuzz case: the
    point is that abuse in one case must not degrade service for the
    next."""
    srv = StreamingServer(make_ds(), RoundRobinPicker())
    gserver = grpc.server(futures.ThreadPoolExecutor(max_workers=8))
    add_extproc_service(gserver, srv)
    port = gserver.add_insecure_port("127.0.0.1:0")
    gserver.start()
    channel = grpc.insecure_channel(f"127.0.0.1:{port}")
    raw = channel.stream_stream(
        f"/{SERVICE_NAME}/Process",
        request_serializer=_identity,
        response_deserializer=_identity,
    )
    yield raw
    channel.close()
    gserver.stop(0)


def good_frame() -> bytes:
    return headers_msg().SerializeToString()


def assert_still_serving(raw) -> None:
    """The canary: a well-formed stream gets a destination header."""
    out = list(raw(iter([good_frame()]), timeout=30))
    assert len(out) == 1
    resp = pb.ProcessingResponse.FromString(out[0])
    assert dest_header(resp)


def test_truncated_frames_fail_cleanly(live):
    frame = good_frame()
    for cut in (1, len(frame) // 3, len(frame) - 1):
        with pytest.raises(grpc.RpcError):
            list(live(iter([frame[:cut]]), timeout=30))
    assert_still_serving(live)


def test_unknown_fields_are_ignored(live):
    """proto3 contract: unknown fields in a ProcessingRequest must be
    skipped, not rejected — new Envoy versions add fields freely."""
    frame = good_frame()
    # field 900 varint, field 901 length-delimited blob, field 902 fixed64
    unknown = (
        bytes([0xA0, 0x38]) + b"\x2a"
        + bytes([0xAA, 0x38]) + bytes([5]) + b"hello"
        + bytes([0xB1, 0x38]) + b"\x01\x02\x03\x04\x05\x06\x07\x08"
    )
    out = list(live(iter([frame + unknown]), timeout=30))
    assert len(out) == 1
    assert dest_header(pb.ProcessingResponse.FromString(out[0]))


def test_random_blobs_never_kill_the_server(live):
    rng = random.Random(1234)
    for _ in range(20):
        blob = rng.randbytes(rng.randint(1, 200))
        try:
            list(live(iter([blob]), timeout=30))
        except grpc.RpcError:
            pass  # clean transport/deserializer error is the contract
    assert_still_serving(live)


def test_out_of_order_phases(live):
    """Response-phase frames before any request phase, duplicated phases,
    body before headers — each stream ends cleanly (responses or a clean
    RpcError), and the server keeps serving."""
    resp_headers = pb.ProcessingRequest(
        response_headers=pb.HttpHeaders()).SerializeToString()
    resp_body = pb.ProcessingRequest(
        response_body=pb.HttpBody(body=b"x", end_of_stream=True)
    ).SerializeToString()
    req_body = pb.ProcessingRequest(
        request_body=pb.HttpBody(body=b"{}", end_of_stream=True)
    ).SerializeToString()
    hdrs = good_frame()
    sequences = [
        [resp_headers, hdrs],
        [resp_body],
        [req_body],             # body with no preceding headers
        [hdrs, hdrs],           # duplicate header phase
        [resp_body, resp_headers, req_body],
    ]
    for seq in sequences:
        try:
            for frame in live(iter(seq), timeout=30):
                resp = pb.ProcessingResponse.FromString(frame)
                assert resp.WhichOneof("response") is not None
        except grpc.RpcError:
            pass
    assert_still_serving(live)


def test_midstream_disconnect_during_deferred_header_pick(live):
    """Envoy dies between the header phase (end_of_stream=False — the
    server defers the pick for the body) and the body: the handler thread
    must unwind, not accumulate."""
    deferred = headers_msg(end_of_stream=False).SerializeToString()
    before = threading.active_count()
    stops = []
    for _ in range(10):
        feeding = threading.Event()
        stop = threading.Event()
        stops.append(stop)

        def frames(feeding=feeding, stop=stop):
            yield deferred
            feeding.set()
            stop.wait(30)  # never send the body; released after cancel

        call = live(frames())
        feeding.wait(timeout=10)
        time.sleep(0.05)  # let the server enter its deferred-pick wait
        call.cancel()
        stop.set()  # release the feeder thread promptly
    # Handler threads unwound (pool reuse allowed; no unbounded growth).
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        if threading.active_count() <= before + 12:
            break
        time.sleep(0.2)
    assert threading.active_count() <= before + 12, threading.active_count()
    assert_still_serving(live)


def test_empty_frame_is_survivable(live):
    """An empty bytes payload parses as a ProcessingRequest with no phase
    set — the server may answer or error, but must not die."""
    try:
        list(live(iter([b""]), timeout=30))
    except grpc.RpcError:
        pass
    assert_still_serving(live)


def test_interleaved_abuse_and_service(live):
    """Malformed and well-formed streams interleaved on the same server:
    every well-formed one succeeds regardless of neighbours."""
    rng = random.Random(99)
    for i in range(12):
        if i % 3 == 2:
            assert_still_serving(live)
        else:
            blob = rng.randbytes(rng.randint(1, 80))
            try:
                list(live(iter([blob, good_frame()]), timeout=30))
            except grpc.RpcError:
                pass
    assert_still_serving(live)
