"""Autoscaling recommender: unit + closed-loop coverage.

Unit tier pins each stage of the loop (signal derivation and staleness,
capacity EWMA + SLO cross-check, recommender hysteresis / cooldown /
bounds, actuator SSA patch + gates); the closed-loop test ramps an
open-loop load ~3x past the initial fleet's capacity against VLLMStub
pods with the actuator writing a Deployment on the in-process fake
apiserver, and asserts shed converges under the bound, leader gating
holds writes back, and scale-down never flaps (docs/AUTOSCALE.md).
"""

from types import SimpleNamespace

import numpy as np

from gie_tpu.autoscale import (
    AutoscaleController,
    AutoscaleRecommender,
    CapacityModel,
    PoolSignals,
    Recommendation,
    RecommenderConfig,
    ReplicaActuator,
    SignalCollector,
)
from gie_tpu.metricsio import MetricsStore
from gie_tpu.runtime import metrics as own_metrics
from gie_tpu.sched import constants as C
from tests.fakeapi import FakeKubeApiServer


def _eps(n):
    return [SimpleNamespace(slot=i) for i in range(n)]


def _signals(**kw):
    base = dict(
        at=0.0, window_s=1.0, ready_replicas=4, queue_depth_total=0.0,
        kv_cache_util_mean=0.1, saturated_fraction=0.0,
        flow_queue_depth=0.0, admitted_per_s=0.0, shed_per_s=0.0,
        shed_per_s_by_band={}, evict_per_s=0.0, pipeline_occupancy=0.0,
        device_wait_share=0.0, metrics_age_max_s=0.1, stale=False,
    )
    base.update(kw)
    return PoolSignals(**base)


# -- signals ---------------------------------------------------------------


def test_signal_collector_windows_counters_into_rates():
    store = MetricsStore()
    store.update(0, {int(C.Metric.QUEUE_DEPTH): 130.0,
                     int(C.Metric.KV_CACHE_UTIL): 0.5}, now=99.9)
    store.update(1, {int(C.Metric.QUEUE_DEPTH): 2.0,
                     int(C.Metric.KV_CACHE_UTIL): 0.2}, now=99.9)
    coll = SignalCollector(
        store, lambda: _eps(2), queue_limit=128.0, kv_limit=0.95,
        staleness_s=2.0)
    assert coll.sample(now=100.0) is None  # first sample = baseline only
    for _ in range(30):
        own_metrics.PICKS.labels(outcome="ok").inc()
    for _ in range(10):
        own_metrics.QUEUE_SHED.labels(reason="depth", band="standard").inc()
    for _ in range(5):
        own_metrics.QUEUE_SHED.labels(reason="evicted", band="sheddable").inc()
    own_metrics.DEVICE_WAIT.observe(0.3)
    own_metrics.HOST_ASSEMBLY.observe(0.1)
    # Fresh scrape inside the window so the sample is not stale.
    store.update(0, {int(C.Metric.QUEUE_DEPTH): 130.0,
                     int(C.Metric.KV_CACHE_UTIL): 0.5}, now=109.9)
    store.update(1, {int(C.Metric.QUEUE_DEPTH): 2.0,
                     int(C.Metric.KV_CACHE_UTIL): 0.2}, now=109.9)
    s = coll.sample(now=110.0)
    assert s.window_s == 10.0
    assert s.ready_replicas == 2
    np.testing.assert_allclose(s.admitted_per_s, 3.0)
    np.testing.assert_allclose(s.shed_per_s, 1.5)  # 10 depth + 5 evicted
    np.testing.assert_allclose(s.shed_per_s_by_band["standard"], 1.0)
    np.testing.assert_allclose(s.evict_per_s, 0.5)
    np.testing.assert_allclose(s.pipeline_occupancy, 0.75)  # 0.3/(0.3+0.1)
    assert s.queue_depth_total == 132.0
    assert s.saturated_fraction == 0.5  # slot 0 past queue_limit
    assert not s.stale


def test_signal_collector_staleness_old_scrape_and_never_scraped():
    store = MetricsStore()
    store.update(0, {int(C.Metric.QUEUE_DEPTH): 1.0}, now=100.0)
    coll = SignalCollector(store, lambda: _eps(1), staleness_s=2.0)
    coll.sample(now=101.0)
    assert not coll.sample(now=101.5).stale        # age 1.5 < 2.0
    assert coll.sample(now=110.0).stale            # age 10 > 2.0

    # A never-scraped slot is infinitely old, not optimistically idle.
    coll2 = SignalCollector(store, lambda: _eps(2), staleness_s=2.0)
    coll2.sample(now=100.5)
    s = coll2.sample(now=101.0)
    assert s.metrics_age_max_s == np.inf and s.stale

    # An empty pool has nothing to be stale about.
    coll3 = SignalCollector(store, lambda: [], staleness_s=2.0)
    coll3.sample(now=100.0)
    assert not coll3.sample(now=101.0).stale


# -- capacity model --------------------------------------------------------


def test_capacity_model_learns_only_near_saturation():
    m = CapacityModel(alpha=1.0, default_per_replica=8.0)
    # Unsaturated sample: throughput is demand, not capacity -> no update.
    m.update(_signals(admitted_per_s=4.0, ready_replicas=4))
    assert not m.converged and m.per_replica() == 8.0
    # Saturated sample: 60 admitted / 4 replicas -> 15 each.
    m.update(_signals(admitted_per_s=60.0, ready_replicas=4,
                      saturated_fraction=1.0))
    assert m.converged
    np.testing.assert_allclose(m.per_replica(), 15.0)
    # Shedding alone also marks the sample as near saturation.
    m2 = CapacityModel(alpha=1.0)
    m2.update(_signals(admitted_per_s=40.0, ready_replicas=4,
                       shed_per_s=3.0))
    np.testing.assert_allclose(m2.per_replica(), 10.0)
    # Stale samples never update the estimate.
    m2.update(_signals(admitted_per_s=400.0, ready_replicas=4,
                       saturated_fraction=1.0, stale=True))
    np.testing.assert_allclose(m2.per_replica(), 10.0)


def test_capacity_model_slo_headroom_derates_without_poisoning_ewma():
    m = CapacityModel(alpha=1.0)
    sat = _signals(admitted_per_s=60.0, ready_replicas=4,
                   saturated_fraction=1.0)
    m.update(sat)
    # Predictor says TTFT 2x the SLO: capacity-for-goodput halves...
    cap = m.update(sat, predicted_ttft_s=2.0, ttft_slo_s=1.0)
    np.testing.assert_allclose(cap, 7.5)
    assert m.replicas_for(60.0, target_utilization=1.0) == 8  # was 4
    # ...but the raw EWMA recovers as soon as latency does.
    np.testing.assert_allclose(m.update(sat), 15.0)


# -- recommender -----------------------------------------------------------


def _rec(cfg=None, per_replica=10.0):
    model = CapacityModel(default_per_replica=per_replica)
    return AutoscaleRecommender(
        cfg if cfg is not None else RecommenderConfig(
            min_replicas=1, max_replicas=16, shed_high_per_s=0.5,
            up_sustain_s=2.0, down_cooldown_s=30.0),
        model)


def test_recommender_fast_up_requires_sustained_shed():
    r = _rec()
    shedding = _signals(admitted_per_s=38.0, shed_per_s=4.0,
                        ready_replicas=4, saturated_fraction=1.0)
    # t=0: shed seen, sustain clock starts -> hold.
    assert r.observe(shedding, 4, now=0.0).direction == "hold"
    # t=1: still inside the sustain window -> hold (blip rejection).
    assert r.observe(shedding, 4, now=1.0).direction == "hold"
    # t=2.5: sustained -> scale up toward demand/capacity.
    rec = r.observe(shedding, 4, now=2.5)
    assert rec.direction == "up" and rec.desired > 4
    # A shed gap resets the sustain clock.
    r2 = _rec()
    r2.observe(shedding, 4, now=0.0)
    r2.observe(_signals(ready_replicas=4), 4, now=1.0)   # calm sample
    assert r2.observe(shedding, 4, now=3.0).direction == "hold"


def test_recommender_up_step_bounded():
    cfg = RecommenderConfig(min_replicas=1, max_replicas=64,
                            shed_high_per_s=0.5, up_sustain_s=0.0,
                            max_up_step=4)
    r = _rec(cfg, per_replica=1.0)  # demand 100/s -> wants ~134 replicas
    rec = r.observe(
        _signals(admitted_per_s=40.0, shed_per_s=60.0, ready_replicas=4,
                 saturated_fraction=1.0), 4, now=0.0)
    assert rec.desired == 8  # current + max_up_step, not the full jump


def test_recommender_slow_down_cooldown_and_flap_damping():
    cfg = RecommenderConfig(min_replicas=2, max_replicas=16,
                            shed_high_per_s=0.5, up_sustain_s=0.0,
                            down_cooldown_s=30.0)
    r = _rec(cfg, per_replica=10.0)
    idle = _signals(admitted_per_s=4.0, ready_replicas=8)
    # util 4/80 = 0.05 < 0.5 -> one step down...
    rec = r.observe(idle, 8, now=0.0)
    assert rec.direction == "down" and rec.desired == 7
    # ...then nothing until the cooldown elapses, no matter how idle.
    for t in (1.0, 10.0, 29.0):
        assert r.observe(idle, 7, now=t).direction == "hold"
    rec = r.observe(idle, 7, now=31.0)
    assert rec.direction == "down" and rec.desired == 6
    # An up-scale also pushes the down cooldown (flap damping).
    r.observe(_signals(admitted_per_s=50.0, shed_per_s=9.0,
                       ready_replicas=6, saturated_fraction=1.0),
              6, now=40.0)
    assert r.observe(idle, 10, now=60.0).direction == "hold"


def test_recommender_hysteresis_band_holds_mid_utilization():
    cfg = RecommenderConfig(min_replicas=1, max_replicas=16,
                            shed_high_per_s=0.5, up_sustain_s=0.0,
                            down_cooldown_s=0.0,
                            target_utilization=0.75,
                            scale_down_utilization=0.5)
    r = _rec(cfg, per_replica=10.0)
    # util 0.6: above the down threshold, below pressure -> hold forever.
    mid = _signals(admitted_per_s=24.0, ready_replicas=4)
    for t in range(5):
        assert r.observe(mid, 4, now=float(t)).direction == "hold"


def test_recommender_fast_up_waits_for_requested_capacity():
    """Pressure while ready < current means the pods from the last step
    are still booting: re-asking every cycle would ratchet the spec to
    max_replicas blind. The fast path waits for the requested capacity
    to materialize, then resumes if pressure persists."""
    cfg = RecommenderConfig(min_replicas=1, max_replicas=16,
                            shed_high_per_s=0.5, up_sustain_s=0.0)
    r = _rec(cfg)
    booting = _signals(admitted_per_s=30.0, shed_per_s=10.0,
                       ready_replicas=2, saturated_fraction=1.0)
    assert r.observe(booting, 6, now=0.0).direction == "hold"
    assert r.observe(booting, 6, now=1.0).direction == "hold"
    ready = _signals(admitted_per_s=30.0, shed_per_s=10.0,
                     ready_replicas=6, saturated_fraction=1.0)
    assert r.observe(ready, 6, now=2.0).direction == "up"


def test_recommender_all_not_ready_idle_pool_does_not_scale():
    """ready==0 with current>0 (rolling restart, zero traffic) makes
    utilization meaningless (inf) — an idle fleet must not scale toward
    max_replicas on it."""
    r = _rec(RecommenderConfig(min_replicas=1, max_replicas=16,
                               shed_high_per_s=0.5, up_sustain_s=0.0))
    restart = _signals(ready_replicas=0, admitted_per_s=0.0)
    for t in range(4):
        assert r.observe(restart, 4, now=float(t)).direction == "hold"


def test_controller_wires_ttft_probe_into_capacity_derate():
    """The production loop feeds the latency predictor's forecast into
    the capacity model: a probe reporting TTFT past the SLO derates
    per-replica capacity on the very next step."""
    store = MetricsStore()
    store.update(0, {int(C.Metric.QUEUE_DEPTH): 1.0}, now=99.9)
    coll = SignalCollector(store, lambda: _eps(1), staleness_s=2.0)
    model = CapacityModel(default_per_replica=8.0)
    recommender = AutoscaleRecommender(RecommenderConfig(), model)
    controller = AutoscaleController(
        coll, recommender, ReplicaActuator(None, "default", None),
        ttft_probe=lambda: (2.0, 1.0))  # predicted 2s vs 1s SLO
    assert controller.step(now=100.0) is None  # collector baseline
    store.update(0, {int(C.Metric.QUEUE_DEPTH): 1.0}, now=100.9)
    assert controller.step(now=101.0) is not None
    np.testing.assert_allclose(model.per_replica(), 4.0)  # 8.0 * (1/2)


def test_recommender_zero_pods_bootstraps_to_min():
    r = _rec(RecommenderConfig(min_replicas=3, max_replicas=16))
    rec = r.observe(_signals(ready_replicas=0), 0, now=0.0)
    assert rec.desired == 3 and rec.reason == "bootstrap"


def test_recommender_scale_to_zero_does_not_flap():
    """min_replicas=0 means scale-to-zero is the operator's intent: an
    empty pool at zero demand must STAY at 0, not bounce 0<->1 through
    the bootstrap path every cooldown."""
    r = _rec(RecommenderConfig(min_replicas=0, max_replicas=8,
                               down_cooldown_s=0.0))
    empty = _signals(ready_replicas=0, admitted_per_s=0.0)
    for t in range(3):
        rec = r.observe(empty, 0, now=float(t))
        assert rec.desired == 0 and rec.direction == "hold"


def test_controller_follower_samples_but_never_recommends():
    """A follower EPP's pick counters never move (ext-proc readiness is
    NOT_SERVING), so its local view reads as utilization 0 — the loop
    must keep sampling (fresh baselines for promotion) but never export
    recommendations from that view."""
    from gie_tpu.autoscale.actuator import ReplicaActuator

    store = MetricsStore()
    coll = SignalCollector(store, lambda: _eps(2), staleness_s=60.0)
    rec = AutoscaleRecommender(RecommenderConfig(
        min_replicas=1, max_replicas=8, down_cooldown_s=0.0))
    leading = {"v": False}
    ctrl = AutoscaleController(
        coll, rec, ReplicaActuator(None, "default", None, dry_run=True),
        is_leader=lambda: leading["v"])
    for slot in range(2):
        store.update(slot, {int(C.Metric.QUEUE_DEPTH): 1.0}, now=99.0)
    assert ctrl.step(now=100.0) is None    # baseline window
    assert ctrl.step(now=101.0) is None    # follower: sampled, no rec
    assert ctrl.step(now=102.0) is None
    # Promotion: the very next step recommends off a FRESH window, not a
    # 3-cycle-old baseline.
    leading["v"] = True
    out = ctrl.step(now=103.0)
    assert out is not None and out.at == 103.0


def test_recommender_wake_from_zero_scales_one():
    """Scale-FROM-zero (ROADMAP): a request 503'd against an empty pool is
    the wake signal — immediate 0->1, no sustain window."""
    r = _rec(RecommenderConfig(min_replicas=0, max_replicas=8))
    rec = r.observe(
        _signals(ready_replicas=0, wake_arrivals=1), 0, now=0.0)
    assert rec.desired == 1 and rec.direction == "up"
    assert "wake-from-zero" in rec.reason
    # Quiet again next window -> stays wherever the actuator took it; an
    # empty pool with NO arrivals still holds at 0 (no flap).
    rec2 = r.observe(_signals(ready_replicas=0), 0, now=1.0)
    assert rec2.desired == 0 and rec2.direction == "hold"


def test_empty_pool_arrival_flows_store_to_recommendation():
    """End-to-end wake path: ext-proc records the 503'd first arrival in
    MetricsStore, the collector drains it into the next window's signals,
    and the recommender turns it into a 0->1 recommendation."""
    store = MetricsStore()
    coll = SignalCollector(store, lambda: [], staleness_s=2.0)
    assert coll.sample(now=100.0) is None      # baseline window
    store.note_empty_pool_arrival()            # the 503'd request
    sig = coll.sample(now=101.0)
    assert sig is not None and sig.wake_arrivals == 1 and not sig.stale
    rec = _rec(RecommenderConfig(min_replicas=0, max_replicas=8)).observe(
        sig, 0, now=101.0)
    assert rec.desired == 1 and "wake-from-zero" in rec.reason
    # Drained: the arrival is observed by exactly one window.
    sig2 = coll.sample(now=102.0)
    assert sig2.wake_arrivals == 0


def test_picker_records_empty_pool_arrival():
    """BatchingTPUPicker.pick with no candidates (empty pool) must note
    the arrival before raising UNAVAILABLE — that 503 IS the wake-from-
    zero traffic signal."""
    import grpc
    import pytest as _pytest

    from gie_tpu.datastore import Datastore
    from gie_tpu.extproc.server import ExtProcError, PickRequest
    from gie_tpu.sched.batching import BatchingTPUPicker
    from gie_tpu.sched.profile import Scheduler

    store = MetricsStore()
    picker = BatchingTPUPicker(Scheduler(), Datastore(), store)
    try:
        with _pytest.raises(ExtProcError) as exc:
            picker.pick(PickRequest(headers={}, body=None), [])
        assert exc.value.code == grpc.StatusCode.UNAVAILABLE
        assert store.take_wake_arrivals() == 1
    finally:
        picker.close()


def test_capacity_model_save_restore_seeds_estimate(tmp_path):
    """ROADMAP (persisted capacity): a converged EWMA written on leader
    shutdown seeds a restarted EPP's model instead of the default."""
    m = CapacityModel(default_per_replica=8.0)
    m.update(_signals(saturated_fraction=0.9, admitted_per_s=20.0,
                      ready_replicas=4))
    assert m.converged
    m.save(str(tmp_path / "cap"))
    m2 = CapacityModel(default_per_replica=8.0)
    assert m2.restore(str(tmp_path / "cap"))
    assert m2.converged
    assert m2.per_replica() == m.per_replica() == 5.0
    # No checkpoint -> unconverged default behavior unchanged.
    m3 = CapacityModel(default_per_replica=8.0)
    assert not m3.restore(str(tmp_path / "missing"))
    assert not m3.converged and m3.per_replica() == 8.0
    # An UNCONVERGED model saves NaN and restores unconverged.
    m3.save(str(tmp_path / "cold"))
    m4 = CapacityModel(default_per_replica=8.0)
    assert m4.restore(str(tmp_path / "cold"))
    assert not m4.converged and m4.per_replica() == 8.0


def test_recommender_stale_holds_exactly_current():
    r = _rec(RecommenderConfig(min_replicas=2, max_replicas=4))
    stale = _signals(ready_replicas=8, admitted_per_s=1000.0,
                     shed_per_s=50.0, stale=True)
    # Never scale on stale data: not up (despite huge shed), not down,
    # not even a bounds clamp (current 8 > max 4 stays 8).
    rec = r.observe(stale, 8, now=0.0)
    assert rec.desired == 8 and rec.reason == "hold-stale"
    assert r.observe(None, 8, now=1.0).desired == 8  # no window yet


def test_recommender_min_max_clamping():
    cfg = RecommenderConfig(min_replicas=2, max_replicas=6,
                            shed_high_per_s=0.5, up_sustain_s=0.0,
                            down_cooldown_s=0.0)
    r = _rec(cfg, per_replica=1.0)
    # Massive shed at current=5: wants far more than 6 -> clamps to max.
    rec = r.observe(
        _signals(admitted_per_s=5.0, shed_per_s=95.0, ready_replicas=5,
                 saturated_fraction=1.0), 5, now=0.0)
    assert rec.desired == 6
    # Idle at min: never below min_replicas.
    r2 = _rec(cfg, per_replica=10.0)
    rec = r2.observe(_signals(admitted_per_s=0.1, ready_replicas=2),
                     2, now=0.0)
    assert rec.desired == 2 and rec.direction == "hold"
    # Out-of-bounds current (operator scaled by hand) clamps back in
    # (utilization mid-band, so neither pressure nor scale-down fires).
    r3 = _rec(cfg, per_replica=10.0)
    rec = r3.observe(_signals(admitted_per_s=60.0, ready_replicas=9),
                     9, now=0.0)
    assert rec.desired == 6 and rec.reason == "bounds-clamp"


# -- actuator --------------------------------------------------------------


def _deployment(name="stub-fleet", replicas=2):
    return {
        "apiVersion": "apps/v1", "kind": "Deployment",
        "metadata": {"name": name, "namespace": "default"},
        "spec": {"replicas": replicas,
                 "selector": {"matchLabels": {"app": "stub"}},
                 "template": {"metadata": {"labels": {"app": "stub"}}}},
    }


def _client(api):
    from gie_tpu.controller.kube import KubeClusterClient

    return KubeClusterClient("default", "pool", server=api.url, token="t")


def test_actuator_ssa_patch_scoped_to_replicas():
    api = FakeKubeApiServer()
    try:
        api.apply("deployments", _deployment(replicas=2))
        act = ReplicaActuator(_client(api), "default", "stub-fleet")
        assert act.current_replicas() == 2
        out = act.apply(Recommendation(0.0, 2, 5, "test"))
        assert out == "patched"
        dep = api._objects[("deployments", "default", "stub-fleet")]
        assert dep["spec"]["replicas"] == 5
        # The single-field patch must not wipe the rest of the spec.
        assert dep["spec"]["selector"] == {"matchLabels": {"app": "stub"}}
        assert act.apply(Recommendation(0.0, 5, 5, "noop")) == "noop"
    finally:
        api.close()


def test_actuator_gates_leader_dry_run_and_missing_target():
    api = FakeKubeApiServer()
    try:
        api.apply("deployments", _deployment(replicas=2))
        leader = {"v": False}
        act = ReplicaActuator(
            _client(api), "default", "stub-fleet",
            is_leader=lambda: leader["v"])
        rec = Recommendation(0.0, 2, 4, "test")
        # Follower: full loop runs, nothing writes.
        assert act.apply(rec) == "not_leader"
        assert api._objects[
            ("deployments", "default", "stub-fleet")]["spec"]["replicas"] == 2
        leader["v"] = True
        assert act.apply(rec) == "patched"

        dry = ReplicaActuator(_client(api), "default", "stub-fleet",
                              dry_run=True)
        assert dry.apply(Recommendation(0.0, 4, 8, "test")) == "dry_run"
        assert api._objects[
            ("deployments", "default", "stub-fleet")]["spec"]["replicas"] == 4

        # Unknown Deployment: current is None, apply degrades gracefully.
        gone = ReplicaActuator(_client(api), "default", "missing")
        assert gone.current_replicas() is None
        assert gone.apply(rec) == "error"
        none = ReplicaActuator(None, "default", None)
        assert none.current_replicas() is None
        assert none.apply(rec) == "no_target"
    finally:
        api.close()


# -- closed loop -----------------------------------------------------------


class _StubFleet:
    """Harness half of the closed loop: VLLMStub pods reconciled to the
    Deployment's patched replica count, a least-loaded router that sheds
    into the REAL runtime counters when every stub's queue is past the
    limit, and the real scrape pipeline into a MetricsStore."""

    def __init__(self, api, queue_limit):
        from gie_tpu.utils.lora import LoraRegistry

        self.api = api
        self.queue_limit = queue_limit
        self.store = MetricsStore()
        self.lora = LoraRegistry()
        self.stubs = []
        self.shed_times = []

    def endpoints(self):
        return _eps(len(self.stubs))

    def reconcile(self):
        """Match the stub fleet to the Deployment's configured replicas
        (what a real Deployment controller would do with pods)."""
        from gie_tpu.simulator import StubConfig, VLLMStub

        dep = self.api._objects[("deployments", "default", "stub-fleet")]
        want = int(dep["spec"]["replicas"])
        while len(self.stubs) < want:
            self.stubs.append(
                VLLMStub(StubConfig(), name=f"pod-{len(self.stubs)}"))
        while len(self.stubs) > want:
            self.stubs.pop()
            self.store.remove(len(self.stubs))

    def route(self, clock, n_new, prompt, decode_tokens):
        for _ in range(n_new):
            load = [len(s.queue) + len(s.running) for s in self.stubs]
            target = self.stubs[int(np.argmin(load))]
            if len(target.queue) >= self.queue_limit:
                own_metrics.QUEUE_SHED.labels(
                    reason="depth", band="standard").inc()
                self.shed_times.append(clock)
            else:
                target.submit(prompt, decode_tokens=decode_tokens)
                own_metrics.PICKS.labels(outcome="ok").inc()

    def step(self, dt):
        for stub in self.stubs:
            stub.step(dt)

    def scrape(self, clock):
        from gie_tpu.metricsio.mappings import VLLM
        from gie_tpu.metricsio.scrape import parse_scrape

        for slot, stub in enumerate(self.stubs):
            metrics, active, waiting = parse_scrape(
                stub.metrics_text(), VLLM, self.lora)
            self.store.update(slot, metrics, lora_active=active,
                              lora_waiting=waiting, now=clock)

    def shed_rate(self, t0, t1):
        n = sum(1 for t in self.shed_times if t0 <= t < t1)
        return n / max(t1 - t0, 1e-9)


def test_closed_loop_scale_up_then_calm_scale_down():
    """Acceptance loop (ISSUE 2): open-loop load ~3x past the initial
    2-stub fleet's capacity; the recommender must add stub replicas via
    the fake apiserver until steady-state shed falls under the bound,
    honor leader gating, and after the ramp scale down without flapping
    (at most one downward step per cooldown window, never back up)."""
    QUEUE_LIMIT = 24.0
    SHED_HIGH = 2.0
    COOLDOWN = 8.0
    RAMP_END = 25.0
    LEADER_AT = 4.0
    END = 60.0
    # One stub: 8 slots x 60 tok/s / 32-token answers ~= 15 req/s; the
    # ramp offers 90 req/s against the initial 2-stub ~30 req/s.
    HIGH_QPS, LOW_QPS = 90.0, 2.0
    prompt = b"x" * 512

    api = FakeKubeApiServer()
    try:
        api.apply("deployments", _deployment(replicas=2))
        fleet = _StubFleet(api, QUEUE_LIMIT)
        fleet.reconcile()
        leader = {"v": False}
        collector = SignalCollector(
            fleet.store, fleet.endpoints, queue_limit=QUEUE_LIMIT,
            kv_limit=0.95, staleness_s=1.0)
        recommender = AutoscaleRecommender(RecommenderConfig(
            min_replicas=1, max_replicas=12, shed_high_per_s=SHED_HIGH,
            up_sustain_s=1.0, max_up_step=4, down_cooldown_s=COOLDOWN,
            target_utilization=0.75, scale_down_utilization=0.5))
        actuator = ReplicaActuator(
            _client(api), "default", "stub-fleet",
            is_leader=lambda: leader["v"])
        controller = AutoscaleController(collector, recommender, actuator)

        rng = np.random.default_rng(7)
        dt = 0.05
        clock, next_scrape, next_ctrl = 0.0, 0.0, 1.0
        replica_log = [(0.0, 2)]   # (time, configured replicas) on change
        gated_up_recs = 0
        while clock < END:
            qps = HIGH_QPS if clock < RAMP_END else LOW_QPS
            fleet.route(clock, rng.poisson(qps * dt), prompt, 32.0)
            fleet.step(dt)
            clock = round(clock + dt, 10)
            if clock >= next_scrape:
                fleet.scrape(clock)
                next_scrape += 0.25
            if clock >= next_ctrl:
                leader["v"] = clock >= LEADER_AT
                rec = controller.step(now=clock)
                if rec is not None and not leader["v"]:
                    if rec.direction == "up":
                        gated_up_recs += 1
                dep = api._objects[
                    ("deployments", "default", "stub-fleet")]
                if int(dep["spec"]["replicas"]) != replica_log[-1][1]:
                    replica_log.append(
                        (clock, int(dep["spec"]["replicas"])))
                fleet.reconcile()
                next_ctrl += 1.0

        # Leader gating honored: the follower phase produced scale-up
        # recommendations (pressure was real) yet wrote nothing.
        assert gated_up_recs >= 1, "no gated recommendation to verify"
        assert all(t >= LEADER_AT for t, _ in replica_log[1:]), (
            f"replicas changed before leadership: {replica_log}")

        # The loop scaled up, and by late-ramp steady state shed sits
        # under the configured bound.
        peak = max(r for _, r in replica_log)
        assert peak >= 5, f"barely scaled: {replica_log}"
        late_shed = fleet.shed_rate(RAMP_END - 5.0, RAMP_END)
        assert late_shed < SHED_HIGH, (
            f"steady-state shed {late_shed:.1f}/s >= {SHED_HIGH}/s "
            f"(replicas {replica_log})")

        # Post-ramp: monotone scale-down (no flap), single steps, at
        # most one per cooldown window.
        post = [(t, r) for t, r in replica_log if t > RAMP_END]
        assert post, f"never scaled down: {replica_log}"
        values = [r for _, r in post]
        assert values == sorted(values, reverse=True), (
            f"scale-down flapped: {replica_log}")
        before = [r for t, r in replica_log if t <= RAMP_END][-1]
        for (t0, r0), (t1, r1) in zip([(RAMP_END, before)] + post, post):
            assert r0 - r1 == 1, f"multi-step down: {replica_log}"
            assert t1 - t0 >= COOLDOWN - 1.05 or t0 == RAMP_END, (
                f"down steps inside one cooldown window: {replica_log}")
    finally:
        api.close()
