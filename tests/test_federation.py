"""gie-fed federation tests (ISSUE 12, docs/FEDERATION.md): digest
sections, the long-poll exchange protocol, era-ordered split-brain
convergence, link robustness (breaker/backoff/staleness), imported
endpoints in the datastore, the spill policy, fault points, and the
live-watch ClusterSet controller over fakeapi."""

from __future__ import annotations

import threading
import time

import numpy as np
import pytest

from gie_tpu.datastore import Datastore
from gie_tpu.datastore.objects import EndpointPool, Pod
from gie_tpu.federation import summary
from gie_tpu.federation.exchange import (
    BREAKER_OPEN,
    CORRUPT,
    DELTA_MISMATCH,
    ERA_REGRESSION,
    FETCH_ERROR,
    INSTALLED,
    NOT_MODIFIED,
    STALE_EPOCH,
    FederationHTTPServer,
    FederationPublisher,
    PeerLink,
    era_str,
)
from gie_tpu.federation.state import FederationState
from gie_tpu.metricsio import MetricsStore
from gie_tpu.replication import codec
from gie_tpu.resilience import faults
from gie_tpu.sched import constants as C

CRIT = int(C.Criticality.CRITICAL)
STD = int(C.Criticality.STANDARD)


def make_datastore(local_pods=1):
    ds = Datastore()
    ds.pool_set(EndpointPool(selector={"app": "x"}, target_ports=[8000],
                             namespace="default"))
    for i in range(local_pods):
        ds.pod_update_or_add(
            Pod(name=f"l{i}", labels={"app": "x"}, ip=f"10.1.0.{i + 1}"))
    return ds


def make_peer_pub(endpoints=None, era=(1, 42), draining=False,
                  cluster="west"):
    eps = endpoints if endpoints is not None else [
        ("10.9.0.1:8000", 1.0, 0.1, False),
        ("10.9.0.2:8000", 2.0, 0.2, False),
    ]
    pub = FederationPublisher({
        summary.META_SECTION: lambda: summary.encode_meta(
            pub.era, draining, cluster),
        summary.LOAD_SECTION: lambda: summary.encode_load(
            list(eps), max_endpoints=64),
    }, era_seq=era[0], era_token=era[1])
    pub.refresh()
    return pub, eps


def make_state(ds=None, **kw):
    ds = ds if ds is not None else make_datastore()
    store = MetricsStore()
    kw.setdefault("cluster", "east")
    kw.setdefault("penalty", 4.0)
    kw.setdefault("spill_queue_limit", 8.0)
    return FederationState(ds, store, **kw), ds, store


def make_link(pub, state, name="west", **kw):
    def fetch(url, since, era, etag, wait_s):
        return pub.serve(since=since, era=era, if_none_match=etag)

    kw.setdefault("wait_s", 0.0)
    kw.setdefault("interval_s", 0.0)
    link = PeerLink(name, "mem://" + name, state.install_peer,
                    fetch=kw.pop("fetch", fetch), **kw)
    state.register_peer(name, link)
    return link


# -- summary sections ------------------------------------------------------


def test_meta_roundtrip_and_malformed():
    arrays = summary.encode_meta((3, 0xDEAD), True, "east-1")
    meta = summary.decode_meta(arrays)
    assert meta.era == (3, 0xDEAD)
    assert meta.draining is True
    assert meta.cluster == "east-1"
    assert summary.decode_meta(None) is None
    assert summary.decode_meta({}) is None
    assert summary.decode_meta(
        {"era": np.zeros(3, np.uint64), "draining": np.uint8(0)}) is None
    # Unknown extra arrays are ignored (forward compat).
    arrays["future_flag"] = np.uint8(1)
    assert summary.decode_meta(arrays) is not None


def test_load_roundtrip_bounds_and_hygiene():
    rows = [(f"10.0.0.{i}:8000", float(i), 0.1 * i, i % 2 == 0)
            for i in range(10)]
    arrays = summary.encode_load(rows, max_endpoints=4)
    assert int(arrays["truncated"]) == 1
    out = summary.decode_load(arrays)
    # Lowest-queue rows kept (the useful spill capacity).
    assert [e.queue_depth for e in out] == [0.0, 1.0, 2.0, 3.0]
    assert out[0].draining is True and out[1].draining is False
    # Hostport hygiene: empty / portless / NaN rows never install.
    bad = summary.encode_load(
        [("not-a-hostport", 1.0, 0.0, False),
         ("10.0.0.1:8000", float("nan"), 0.0, False),
         ("10.0.0.2:8000", 1.0, 0.5, False)], max_endpoints=8)
    out = summary.decode_load(bad)
    assert [e.hostport for e in out] == ["10.0.0.2:8000"]
    assert summary.decode_load({"hostports": np.zeros((2, 8), np.uint8),
                                "queue": np.zeros(1, np.float32),
                                "kv": np.zeros(2, np.float32),
                                "draining": np.zeros(2, np.uint8)}) is None


def test_prefix_roundtrip_drops_zero_keys():
    arrays = summary.encode_prefix(
        np.asarray([0, 7, 9, 0, 11], np.uint32), max_keys=2)
    keys = summary.decode_prefix(arrays)
    assert keys.tolist() == [7, 9]


# -- exchange protocol -----------------------------------------------------


def test_link_install_then_not_modified_then_delta():
    frames = []
    pub, eps = make_peer_pub()
    state, ds, store = make_state()

    def fetch(url, since, era, etag, wait_s):
        status, headers, body = pub.serve(
            since=since, era=era, if_none_match=etag)
        if status == 200:
            frames.append(codec.decode_digest(body))
        return status, headers, body

    link = make_link(pub, state, fetch=fetch)
    assert link.poll_once() == INSTALLED
    assert not frames[0].delta
    assert link.poll_once() == NOT_MODIFIED
    assert link.staleness_s() < 1.0
    # One section changes -> the next frame is a DELTA carrying only it.
    eps.append(("10.9.0.3:8000", 0.0, 0.0, False))
    pub.refresh()
    assert link.poll_once() == INSTALLED
    assert frames[1].delta
    assert set(frames[1].sections) == {summary.LOAD_SECTION}
    assert "10.9.0.3:8000" in [
        e.hostport for e in ds.endpoints() if e.cluster]


def test_long_poll_parks_until_refresh():
    pub, eps = make_peer_pub()
    status0, headers0, _ = pub.serve()
    etag = headers0["ETag"]
    result = {}

    def park():
        t0 = time.monotonic()
        status, _, body = pub.serve(if_none_match=etag, wait_s=5.0)
        result.update(status=status, dt=time.monotonic() - t0,
                      n=len(body))

    t = threading.Thread(target=park)
    t.start()
    time.sleep(0.1)
    eps.append(("10.9.0.9:8000", 0.0, 0.0, False))
    pub.refresh()
    t.join(3)
    assert result["status"] == 200 and result["n"] > 0
    # Woke on the refresh, not the 5 s window.
    assert result["dt"] < 2.0
    # An empty window expires back to 304.
    status, _, _ = pub.serve(if_none_match=pub.serve()[1]["ETag"],
                             wait_s=0.05)
    assert status == 304


def test_link_over_real_http_long_poll():
    pub, eps = make_peer_pub()
    srv = FederationHTTPServer(pub, 0)
    try:
        state, ds, _ = make_state()
        link = PeerLink("west", f"http://127.0.0.1:{srv.port}",
                        state.install_peer, wait_s=0.5, interval_s=0.0)
        state.register_peer("west", link)
        assert link.poll_once() == INSTALLED
        # The long poll parks server-side and wakes on the epoch bump.
        eps.append(("10.9.0.4:8000", 0.0, 0.0, False))

        def bump():
            time.sleep(0.1)
            pub.refresh()

        t = threading.Thread(target=bump)
        t.start()
        t0 = time.monotonic()
        assert link.poll_once() == INSTALLED
        assert time.monotonic() - t0 < 0.45  # woke before the window
        t.join()
    finally:
        srv.close()


# -- era ordering / split brain --------------------------------------------


def test_era_regression_rejected_and_state_kept():
    pub_new, _ = make_peer_pub(era=(2, 50))
    pub_old, _ = make_peer_pub(
        endpoints=[("10.9.9.9:8000", 0.0, 0.0, False)], era=(1, 99))
    state, ds, _ = make_state()
    link = make_link(pub_new, state)
    assert link.poll_once() == INSTALLED
    before = sorted(e.hostport for e in ds.endpoints() if e.cluster)

    def fetch_old(url, since, era, etag, wait_s):
        return pub_old.serve()

    link._fetch = fetch_old
    assert link.poll_once() == ERA_REGRESSION
    assert link.era_regressions == 1
    # Installed lineage untouched: no zombie endpoint appeared.
    assert sorted(e.hostport for e in ds.endpoints() if e.cluster) == before
    assert link.installed_era == (2, 50)


@pytest.mark.parametrize("zombie_first", [True, False])
def test_split_brain_interleave_converges_on_max_era(zombie_first):
    """Frames from both lineages of a healed partition, in either
    interleaving order: the installed era ratchets to max(era) and the
    loser's frames all reject — deterministic convergence."""
    pub_a, _ = make_peer_pub(
        endpoints=[("10.9.1.1:8000", 0.0, 0.0, False)], era=(1, 10))
    pub_b, _ = make_peer_pub(
        endpoints=[("10.9.2.1:8000", 0.0, 0.0, False)], era=(2, 7))
    state, ds, _ = make_state()
    order = [pub_a, pub_b] if zombie_first else [pub_b, pub_a]
    calls = {"n": 0}

    def fetch(url, since, era, etag, wait_s):
        pub = order[calls["n"] % 2]
        calls["n"] += 1
        return pub.serve()

    link = make_link(pub_a, state, fetch=fetch)
    outcomes = [link.poll_once() for _ in range(6)]
    assert link.installed_era == (2, 7)
    assert ERA_REGRESSION in outcomes or STALE_EPOCH in outcomes
    # Only the winning lineage's endpoints are installed.
    remote = sorted(e.hostport for e in ds.endpoints() if e.cluster)
    assert remote == ["10.9.2.1:8000"]


def test_era_flip_mid_delta_forces_full_snapshot():
    pub, eps = make_peer_pub(era=(1, 5))
    state, ds, _ = make_state()
    link = make_link(pub, state)
    assert link.poll_once() == INSTALLED
    # The peer fails over: greater era. The link's next request still
    # asks for a delta against the OLD era; the publisher serves a full
    # snapshot (era mismatch), which must install with the new era.
    pub.bump_era()
    eps.append(("10.9.0.7:8000", 0.0, 0.0, False))
    pub.refresh()
    assert link.poll_once() == INSTALLED
    assert link.installed_era == pub.era
    assert link.era_flips == 1


def test_stale_epoch_replay_rejected():
    pub, _ = make_peer_pub()
    state, _, _ = make_state()
    replay = {}

    def fetch(url, since, era, etag, wait_s):
        if "frame" not in replay:
            replay["frame"] = pub.serve()
        return replay["frame"]  # the same frame forever

    link = make_link(pub, state, fetch=fetch)
    assert link.poll_once() == INSTALLED
    assert link.poll_once() == STALE_EPOCH
    assert link.rejects == 1


def test_full_snapshot_without_meta_rejected():
    state, _, _ = make_state()

    def fetch(url, since, era, etag, wait_s):
        blob = codec.encode_digest(1, {
            summary.LOAD_SECTION: {"hostports": np.zeros((0, 8), np.uint8),
                                   "queue": np.zeros(0, np.float32),
                                   "kv": np.zeros(0, np.float32),
                                   "draining": np.zeros(0, np.uint8)}})
        return 200, {}, blob

    link = PeerLink("west", "mem://x", state.install_peer, fetch=fetch,
                    wait_s=0.0, interval_s=0.0)
    assert link.poll_once() == "rejected"


# -- cross-version forward compat / corruption fuzz ------------------------


def test_unknown_sections_and_arrays_skip_unknown():
    """A NEWER peer ships sections and arrays this build has no home
    for: the frame installs, unknowns are ignored."""
    state, ds, _ = make_state()
    meta = summary.encode_meta((1, 1), False, "west")
    load = summary.encode_load(
        [("10.9.0.1:8000", 1.0, 0.1, False)], max_endpoints=8)
    load["future_column"] = np.ones(1, np.float32)  # unknown array
    blob = codec.encode_digest(1, {
        summary.META_SECTION: meta,
        summary.LOAD_SECTION: load,
        "fed.future-section": {"x": np.arange(4, dtype=np.uint32)},
    })

    def fetch(url, since, era, etag, wait_s):
        return 200, {}, blob

    link = PeerLink("west", "mem://x", state.install_peer, fetch=fetch,
                    wait_s=0.0, interval_s=0.0)
    state.register_peer("west", link)
    assert link.poll_once() == INSTALLED
    assert [e.hostport for e in ds.endpoints() if e.cluster] == [
        "10.9.0.1:8000"]


def test_corrupted_frames_reject_and_keep_state():
    """Byte-flip fuzz across a valid frame through the LINK path: every
    mutation either rejects whole (corrupt/stale/regression) or decodes
    to the identical install — never a partial/garbled install."""
    pub, _ = make_peer_pub()
    state, ds, _ = make_state()
    link = make_link(pub, state)
    assert link.poll_once() == INSTALLED
    baseline = sorted(e.hostport for e in ds.endpoints() if e.cluster)
    status, headers, body = pub.serve()
    rng = np.random.default_rng(7)
    outcomes = set()
    for _ in range(64):
        i = int(rng.integers(len(body)))
        flipped = bytearray(body)
        flipped[i] ^= 1 << int(rng.integers(8))

        def fetch(url, since, era, etag, wait_s, b=bytes(flipped)):
            return 200, dict(headers), b

        link._fetch = fetch
        link._next_poll = 0.0
        link._fail_streak = 0  # keep the breaker out of the fuzz loop
        link._open_until = 0.0
        out = link.poll_once()
        outcomes.add(out)
        assert out in (CORRUPT, STALE_EPOCH, ERA_REGRESSION, "rejected",
                       DELTA_MISMATCH)
        assert sorted(
            e.hostport for e in ds.endpoints() if e.cluster) == baseline
    assert CORRUPT in outcomes  # the CRC guard actually fired


# -- link robustness -------------------------------------------------------


def test_link_breaker_opens_and_half_open_probe_recovers():
    pub, _ = make_peer_pub()
    state, _, _ = make_state()
    broken = {"on": True}

    def fetch(url, since, era, etag, wait_s):
        if broken["on"]:
            raise ConnectionError("severed")
        return pub.serve(since=since, era=era, if_none_match=etag)

    link = make_link(pub, state, fetch=fetch, open_after=3, open_s=0.2)
    now = time.monotonic()
    assert link.poll_once(now) == FETCH_ERROR
    link._next_poll = 0.0
    assert link.poll_once(now) == FETCH_ERROR
    link._next_poll = 0.0
    assert link.poll_once(now) == FETCH_ERROR
    assert link.breaker_open()
    link._next_poll = 0.0
    # One observable breaker_open outcome per dwell, then silence.
    assert link.poll_once() == BREAKER_OPEN
    link._next_poll = 0.0
    assert link.poll_once() is None  # open: no fetch at all
    # Dwell passes; the half-open probe fails -> re-opens.
    link._open_until = 0.0
    link._next_poll = 0.0
    assert link.poll_once() == FETCH_ERROR
    assert link.breaker_open()
    # Peer comes back: the next probe closes the breaker and installs.
    broken["on"] = False
    link._open_until = 0.0
    link._next_poll = 0.0
    assert link.poll_once() == INSTALLED
    assert not link.breaker_open()


def test_staleness_drives_local_only_and_penalty_inflation():
    pub, _ = make_peer_pub()
    clock = {"t": 1000.0}
    state, ds, store = make_state(
        stale_inflate_s=1.0, local_only_after_s=2.0,
        clock=lambda: clock["t"])
    link = make_link(pub, state)
    assert link.poll_once() == INSTALLED
    slots = [e.slot for e in ds.endpoints() if e.cluster]
    fresh_q = store.pool_rows(slots)[0][:, C.Metric.QUEUE_DEPTH].copy()
    # Sever the link; staleness inflates the penalty rows.
    link.last_contact_at = time.monotonic() - 1.5
    clock["t"] += 10.0
    state.observe()
    stale_q = store.pool_rows(slots)[0][:, C.Metric.QUEUE_DEPTH]
    assert np.all(stale_q > fresh_q)
    view = state._peers["west"]
    assert not view.local_only
    # Past the floor: LOCAL-ONLY — rows saturate, spillover excludes.
    link.last_contact_at = time.monotonic() - 5.0
    clock["t"] += 10.0
    state.observe()
    assert view.local_only and view.local_only_spells == 1
    sat_q = store.pool_rows(slots)[0][:, C.Metric.QUEUE_DEPTH]
    assert np.all(sat_q >= state.spill_queue_limit)
    assert state.spill_candidates(
        STD, np.asarray([0]), np.full(64, 99.0)) is None
    # A fresh confirm readmits: the 304 resets the staleness clock and
    # the next observe tick applies the blackout-lift rule.
    link._next_poll = 0.0
    assert link.poll_once() == NOT_MODIFIED
    clock["t"] += 1.0
    state.observe()
    assert not view.local_only


# -- spill policy ----------------------------------------------------------


def install_simple_peer(state, pub=None):
    pub = pub if pub is not None else make_peer_pub()[0]
    link = make_link(pub, state)
    assert link.poll_once() == INSTALLED
    return link


def test_spill_rules_band_and_saturation():
    state, ds, _ = make_state()
    install_simple_peer(state)
    sat = np.full(64, 99.0)
    idle = np.zeros(64)
    local = np.asarray([0])
    # Unsaturated local: nobody spills.
    assert state.spill_candidates(STD, local, idle) is None
    # Saturated local: STANDARD spills, CRITICAL stays home.
    assert state.spill_candidates(STD, local, sat)
    assert state.spill_candidates(CRIT, local, sat) is None
    # No local candidate at all: CRITICAL may cross (availability).
    assert state.spill_candidates(CRIT, np.asarray([], np.int64), sat)


def test_peer_draining_and_drain_mode():
    # A peer that flags DRAINING is excluded from spillover.
    pub_d, _ = make_peer_pub(draining=True)
    state, ds, _ = make_state()
    install_simple_peer(state, pub_d)
    assert state.spill_candidates(STD, np.asarray([0]),
                                  np.full(64, 99.0)) is None
    # Our own drain: remote-first for every band, regardless of load.
    state2, ds2, _ = make_state(ds=make_datastore())
    install_simple_peer(state2)
    state2.draining = True
    out = state2.spill_candidates(CRIT, np.asarray([0]), np.zeros(64))
    assert out and all(e.cluster == "west" for e in out)


def test_capacity_matrix_rows():
    state, ds, _ = make_state()
    install_simple_peer(state)
    matrix = state.capacity_matrix()
    assert matrix["east"]["local"] is True
    assert matrix["east"]["endpoints"] == 1
    west = matrix["west"]
    assert west["endpoints"] == 2 and west["local"] is False
    assert west["era"] == [1, 42]
    assert west["penalty"] >= 0.0 and "staleness_s" in west


def test_prefix_fold_diffs_into_scheduler():
    calls = []

    class FakeScheduler:
        def apply_prefix_events(self, slot, stored, removed):
            calls.append((slot, stored.tolist(), removed.tolist()))

    state, ds, _ = make_state(scheduler=FakeScheduler())
    pub, _ = make_peer_pub()
    link = make_link(pub, state)
    link.poll_once()
    state.install_peer("west", {
        summary.PREFIX_SECTION: summary.encode_prefix(
            np.asarray([5, 6], np.uint32), max_keys=16)}, delta=True)
    slots = sorted(e.slot for e in ds.endpoints() if e.cluster)
    assert sorted(c[0] for c in calls) == slots
    assert all(c[1] == [5, 6] and c[2] == [] for c in calls)
    calls.clear()
    # The next summary drops 5 and adds 7: only the DIFF folds.
    state.install_peer("west", {
        summary.PREFIX_SECTION: summary.encode_prefix(
            np.asarray([6, 7], np.uint32), max_keys=16)}, delta=True)
    assert all(c[1] == [7] and c[2] == [5] for c in calls)


# -- datastore imports -----------------------------------------------------


def test_external_endpoints_lifecycle():
    ds = make_datastore(local_pods=2)
    reclaimed = []
    ds._on_slot_reclaimed = reclaimed.append
    ep = ds.external_upsert("west", "10.9.0.1:8000", "10.9.0.1", 8000)
    assert ep.cluster == "west" and ep.slot >= 0
    assert ds.endpoint_by_hostport("10.9.0.1:8000") is ep
    # Default candidacy excludes imports; endpoints() includes them.
    assert ep not in ds.pick_candidates()
    assert ep in ds.endpoints()
    assert ep not in ds.local_endpoints()
    # Refresh in place keeps the slot sticky.
    ep2 = ds.external_upsert("west", "10.9.0.1:8000", "10.9.0.9", 8000)
    assert ep2.slot == ep.slot and ep2.address == "10.9.0.9"
    ds.external_remove("west", "10.9.0.1:8000")
    assert reclaimed == [ep.slot]
    assert ds.endpoint_by_hostport("10.9.0.9:8000") is None


def test_external_clear_and_resync_skips_imports():
    ds = make_datastore(local_pods=1)
    ds.external_upsert("west", "a", "10.9.0.1", 8000)
    ds.external_upsert("west", "b", "10.9.0.2", 8000)
    ds.external_upsert("north", "c", "10.9.1.1", 8000)
    # A pool resync (selector change) must not evict imports.
    ds.pool_set(EndpointPool(selector={"app": "y"}, target_ports=[8000],
                             namespace="default"), pod_lister=lambda: [])
    assert len([e for e in ds.endpoints() if e.cluster]) == 3
    assert ds.external_clear("west") == 2
    assert sorted(e.cluster for e in ds.endpoints() if e.cluster) == [
        "north"]


def test_pick_candidates_availability_ladder():
    ds = make_datastore(local_pods=1)
    remote = ds.external_upsert("west", "r", "10.9.0.1", 8000)
    local = [e for e in ds.endpoints() if not e.cluster][0]
    # Healthy local wins.
    assert ds.pick_candidates() == [local]
    # Draining local still beats remote (in-flight locality).
    ds.pod_mark_draining("default", "l0")
    assert ds.pick_candidates() == [local]
    # No local at all: healthy remote is the availability floor.
    ds.pod_delete("default", "l0")
    assert ds.pick_candidates() == [remote]


# -- fault points ----------------------------------------------------------


def test_fault_peer_publish_error_and_corrupt():
    pub, _ = make_peer_pub()
    state, _, _ = make_state()
    link = make_link(pub, state)
    faults.install(faults.FaultInjector(
        3, {"peer.publish": faults.FaultRule(p_error=1.0, max_fires=1)}))
    try:
        assert link.poll_once() == FETCH_ERROR  # 503 from the serve side
        link._next_poll = 0.0
        assert link.poll_once() == INSTALLED    # rule exhausted
        faults.install(faults.FaultInjector(
            4, {"peer.publish": faults.FaultRule(
                p_corrupt=1.0, max_fires=1)}))
        link.last_etag = None  # force a body (304 carries none)
        link._want_full = True
        link._next_poll = 0.0
        assert link.poll_once() == CORRUPT      # CRC guard absorbed it
    finally:
        faults.uninstall()


def test_fault_peer_poll_and_partition_scoped_by_key():
    pub, _ = make_peer_pub()
    state, _, _ = make_state()
    link_w = make_link(pub, state, name="west")
    pub_n, _ = make_peer_pub(cluster="north")
    state2, _, _ = make_state(ds=make_datastore())
    link_n = make_link(pub_n, state2, name="north")
    faults.install(faults.FaultInjector(5, {
        "peer.partition": faults.FaultRule(p_error=1.0, keys=("west",)),
        "peer.poll": faults.FaultRule(p_error=0.0),
    }))
    try:
        assert link_w.poll_once() == FETCH_ERROR  # severed
        assert link_n.poll_once() == INSTALLED    # other peer unaffected
        faults.install(faults.FaultInjector(6, {
            "peer.poll": faults.FaultRule(p_error=1.0, max_fires=1)}))
        link_w._fail_streak = 0
        link_w._open_until = 0.0
        link_w._next_poll = 0.0
        assert link_w.poll_once() == FETCH_ERROR  # flaky link point
    finally:
        faults.uninstall()


def test_new_fault_points_registered():
    for point in ("peer.poll", "peer.publish", "peer.partition"):
        assert point in faults.CATALOG


# -- breaker-open pacing ---------------------------------------------------


def test_era_str_wire_form():
    assert era_str((2, 0xAB)) == "2.00000000000000ab"


# -- ClusterSet over live watches (fakeapi) --------------------------------


def _export_pool_manifest(name="pool", export=True):
    from gie_tpu.api import types as api

    annotations = (
        {api.EXPORT_ANNOTATION: api.EXPORT_SCOPE_CLUSTERSET}
        if export else {})
    return {
        "apiVersion": f"{api.GROUP}/{api.VERSION}",
        "kind": "InferencePool",
        "metadata": {"name": name, "namespace": "default",
                     "annotations": annotations},
        "spec": {
            "selector": {"matchLabels": {"app": "vllm"}},
            "targetPorts": [{"number": 8000}],
            "endpointPickerRef": {"name": "epp",
                                  "port": {"number": 9002}},
        },
    }


def _wait(pred, timeout=8.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.05)
    return pred()


def test_clusterset_reconciles_over_live_watches():
    """The ISSUE-12 satellite: InferencePoolImport support in fakeapi +
    the MultiClusterController driving ClusterSet reconciliation
    end-to-end over real watch streams — exported pool in east
    materializes an import in west, carries the Exported condition back
    onto the pool, and prunes the import when the export stops."""
    from fakeapi import FakeKubeApiServer
    from gie_tpu.api import types as api
    from gie_tpu.controller.kube import KubeClusterClient
    from gie_tpu.controller.multicluster import (
        CONTROLLER_NAME,
        MultiClusterController,
    )

    east, west = FakeKubeApiServer(), FakeKubeApiServer()
    ctl = MultiClusterController({
        "east": KubeClusterClient("default", "pool", server=east.url),
        "west": KubeClusterClient("default", "pool", server=west.url),
    })
    ctl.start()
    try:
        east.apply("pools", _export_pool_manifest())
        key = ("imports", "default", "pool")
        assert _wait(lambda: key in west._objects)
        imp = api.import_from_dict(west._objects[key])
        ctrl = imp.status.controllers[0]
        assert ctrl.name == CONTROLLER_NAME
        assert [c.name for c in ctrl.exportingClusters] == ["east"]
        # Never an import in the exporting cluster itself.
        assert key not in east._objects
        # Exported condition patched onto the pool's status.
        assert _wait(lambda: any(
            n == "pool" for _ns, n, _p in east.status_patches))
        # The loop settles: no self-chasing status-patch churn.
        n1 = ctl.reconciles
        time.sleep(0.6)
        assert ctl.reconciles - n1 <= 1
        # Export withdrawn -> the import is pruned.
        east.apply("pools", _export_pool_manifest(export=False))
        assert _wait(lambda: key not in west._objects)
    finally:
        ctl.stop()
        east.close()
        west.close()


def test_import_serializers_roundtrip():
    from gie_tpu.api import types as api

    imp = api.InferencePoolImport(
        metadata=api.ObjectMeta(name="pool", namespace="ns"),
        status=api.InferencePoolImportStatus(controllers=[
            api.ImportController(
                name="c", exportingClusters=[api.ExportingCluster("e")]),
        ]))
    d = api.import_to_dict(imp)
    assert d["kind"] == "InferencePoolImport"
    back = api.import_from_dict(d)
    assert back.metadata.name == "pool"
    assert back.status.controllers[0].exportingClusters[0].name == "e"
    # A status-only object keeps a present (empty) status.
    assert "status" in api.import_to_dict(api.InferencePoolImport(
        metadata=api.ObjectMeta(name="x")))


def test_external_upsert_refuses_local_hostport_collision():
    """Overlapping pod CIDRs across clusters: a peer advertising a
    hostport a LOCAL pod owns is refused — local wins (importing would
    hijack serve-outcome attribution and, on removal, delete the local
    pod's hostport mapping)."""
    ds = make_datastore(local_pods=1)  # local owns 10.1.0.1:8000
    assert ds.external_upsert("west", "clash", "10.1.0.1", 8000) is None
    local = ds.endpoint_by_hostport("10.1.0.1:8000")
    assert local is not None and not local.cluster
    # Non-colliding imports still admit.
    first = ds.external_upsert("west", "ok", "10.9.0.1", 8000)
    assert first is not None
    # Remote-remote collisions refuse too (first owner wins — a second
    # claimant would hijack attribution and delete the mapping on its
    # removal).
    assert ds.external_upsert("north", "dup", "10.9.0.1", 8000) is None
    assert ds.endpoint_by_hostport("10.9.0.1:8000") is first


def test_install_rejects_mismatched_cluster_name():
    """A digest whose fed.meta names a different cluster than the link
    is configured for (typo'd --fed-peer URL) must reject whole."""
    pub, _ = make_peer_pub(cluster="east-actually")
    state, ds, _ = make_state()
    link = make_link(pub, state)  # configured as "west"
    assert link.poll_once() == "rejected"
    assert not [e for e in ds.endpoints() if e.cluster]


def test_clusterset_repairs_out_of_band_import_deletion():
    """Level-triggered imports: an import deleted out-of-band is
    re-created on the next reconcile, and a 404 on DELETE (already
    gone) is treated as success, not retried forever."""
    from fakeapi import FakeKubeApiServer
    from gie_tpu.controller.kube import KubeClusterClient
    from gie_tpu.controller.multicluster import MultiClusterController

    east, west = FakeKubeApiServer(), FakeKubeApiServer()
    ctl = MultiClusterController({
        "east": KubeClusterClient("default", "pool", server=east.url),
        "west": KubeClusterClient("default", "pool", server=west.url),
    })
    ctl.start()
    try:
        east.apply("pools", _export_pool_manifest())
        key = ("imports", "default", "pool")
        assert _wait(lambda: key in west._objects)
        # Out-of-band deletion, then any pool event: repaired.
        west.delete("imports", "default", "pool")
        manifest = _export_pool_manifest()
        manifest["metadata"]["labels"] = {"touched": "1"}
        east.apply("pools", manifest)
        assert _wait(lambda: key in west._objects)
        # Out-of-band deletion + export withdrawn: the DELETE 404 must
        # settle (key forgotten), not error-loop.
        west.delete("imports", "default", "pool")
        east.apply("pools", _export_pool_manifest(export=False))
        assert _wait(lambda: not ctl._written)
        assert key not in west._objects
    finally:
        ctl.stop()
        east.close()
        west.close()
