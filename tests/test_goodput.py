"""Goodput regression: the batched TPU policy must beat the reference's
default least-kv scorer on the cache-constrained prefix benchmark
(BASELINE north star: >= 1.3x; currently 2.15x, asserted at 1.5x)."""

from gie_tpu.simulator import StubConfig
from gie_tpu.simulator.cluster import SimCluster, WorkloadConfig, tuned_scheduler


def run(policy, duration=20.0, seed=0):
    wl = WorkloadConfig(
        arrival_qps=75.0,
        n_sessions=64,
        system_prompt_bytes=8192,
        user_suffix_bytes=128,
        decode_tokens_mean=32.0,
        ttft_slo_s=2.5,
    )
    stub = StubConfig(
        max_running=8,
        prefill_tokens_per_s=4000.0,
        decode_tokens_per_s=50.0,
        prefix_cache_chunks=2048,
    )
    cluster = SimCluster(n_pods=8, stub_cfg=stub, seed=seed)
    sched = tuned_scheduler() if policy == "tpu" else None
    return cluster.run(policy, wl, duration_s=duration, scheduler=sched)


def test_tpu_beats_least_kv_goodput():
    base = run("least-kv")
    tpu = run("tpu")
    assert tpu.prefix_hit_rate > base.prefix_hit_rate + 0.1
    assert tpu.goodput_tokens_per_s > base.goodput_tokens_per_s * 1.5
    assert tpu.ttft_p50_s < base.ttft_p50_s


def test_tpu_beats_least_kv_multilora():
    """BASELINE configs[2]: LoRA-affinity + queue-depth joint scoring must
    dominate the baseline when adapter cold-loads are expensive."""
    wl = WorkloadConfig(
        arrival_qps=70.0,
        n_sessions=64,
        system_prompt_bytes=4096,
        user_suffix_bytes=128,
        decode_tokens_mean=32.0,
        ttft_slo_s=2.5,
        lora_adapters=12,
    )
    stub = StubConfig(
        max_running=8,
        prefill_tokens_per_s=4000.0,
        decode_tokens_per_s=50.0,
        prefix_cache_chunks=2048,
        max_lora=4,
        lora_load_s=0.5,
    )
    results = {}
    for policy in ("least-kv", "tpu"):
        cluster = SimCluster(n_pods=8, stub_cfg=stub, seed=0)
        sched = tuned_scheduler() if policy == "tpu" else None
        results[policy] = cluster.run(policy, wl, duration_s=12.0,
                                      scheduler=sched)
    assert (results["tpu"].goodput_tokens_per_s
            > results["least-kv"].goodput_tokens_per_s * 2.0)
