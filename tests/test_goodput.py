"""Goodput regression: the batched TPU policy must beat the reference's
default least-kv scorer on the cache-constrained prefix benchmark
(BASELINE north star: >= 1.3x; currently 2.15x, asserted at 1.5x)."""

from gie_tpu.simulator import StubConfig
from gie_tpu.simulator.cluster import SimCluster, WorkloadConfig, tuned_scheduler


def run(policy, duration=20.0, seed=0):
    wl = WorkloadConfig(
        arrival_qps=75.0,
        n_sessions=64,
        system_prompt_bytes=8192,
        user_suffix_bytes=128,
        decode_tokens_mean=32.0,
        ttft_slo_s=2.5,
    )
    stub = StubConfig(
        max_running=8,
        prefill_tokens_per_s=4000.0,
        decode_tokens_per_s=50.0,
        prefix_cache_chunks=2048,
    )
    cluster = SimCluster(n_pods=8, stub_cfg=stub, seed=seed)
    sched = tuned_scheduler() if policy == "tpu" else None
    return cluster.run(policy, wl, duration_s=duration, scheduler=sched)


def test_tpu_beats_least_kv_goodput():
    base = run("least-kv")
    tpu = run("tpu")
    assert tpu.prefix_hit_rate > base.prefix_hit_rate + 0.1
    assert tpu.goodput_tokens_per_s > base.goodput_tokens_per_s * 1.5
    assert tpu.ttft_p50_s < base.ttft_p50_s


def test_tpu_beats_least_kv_multilora():
    """BASELINE configs[2]: LoRA-affinity + queue-depth joint scoring must
    dominate the baseline when adapter cold-loads are expensive."""
    wl = WorkloadConfig(
        arrival_qps=70.0,
        n_sessions=64,
        system_prompt_bytes=4096,
        user_suffix_bytes=128,
        decode_tokens_mean=32.0,
        ttft_slo_s=2.5,
        lora_adapters=12,
    )
    stub = StubConfig(
        max_running=8,
        prefill_tokens_per_s=4000.0,
        decode_tokens_per_s=50.0,
        prefix_cache_chunks=2048,
        max_lora=4,
        lora_load_s=0.5,
    )
    results = {}
    for policy in ("least-kv", "tpu"):
        cluster = SimCluster(n_pods=8, stub_cfg=stub, seed=0)
        sched = tuned_scheduler() if policy == "tpu" else None
        results[policy] = cluster.run(policy, wl, duration_s=12.0,
                                      scheduler=sched)
    assert (results["tpu"].goodput_tokens_per_s
            > results["least-kv"].goodput_tokens_per_s * 2.0)


def test_predictor_trains_online_in_sim_without_regression():
    """BASELINE configs[3]: the predictor column learns from real sim
    completions and must not regress goodput."""
    import jax.numpy as jnp

    from gie_tpu.models.latency import (
        LatencyPredictor,
        OnlineTrainer,
        predictor_score_fn,
    )
    from gie_tpu.sched import ProfileConfig, Scheduler, Weights

    p = LatencyPredictor()
    trainer = OnlineTrainer(p, batch_size=64)
    sched = Scheduler(
        ProfileConfig(load_decay=0.95, load_norm=8, queue_norm=16,
                      picker="sinkhorn"),
        weights=Weights(
            queue=jnp.float32(2.0), kv_cache=jnp.float32(1.0),
            prefix=jnp.float32(4.0), lora=jnp.float32(1.0),
            assumed_load=jnp.float32(1.5), latency=jnp.float32(1.0),
        ),
        predictor_fn=predictor_score_fn(p),
        predictor_params=trainer.params,
    )
    base = run("least-kv", duration=12.0)
    wl = WorkloadConfig(
        arrival_qps=75.0, n_sessions=64, system_prompt_bytes=8192,
        user_suffix_bytes=128, decode_tokens_mean=32.0, ttft_slo_s=2.5,
    )
    stub = StubConfig(max_running=8, prefill_tokens_per_s=4000.0,
                      decode_tokens_per_s=50.0, prefix_cache_chunks=2048)
    cluster = SimCluster(n_pods=8, stub_cfg=stub, seed=0)
    stats = cluster.run("tpu", wl, duration_s=12.0, scheduler=sched,
                        trainer=trainer)
    assert trainer.last_loss is not None and trainer.last_loss < 1.0
    assert stats.goodput_tokens_per_s > base.goodput_tokens_per_s * 1.2


def test_session_affinity_lifts_hit_rate():
    """Round-2 session-stickiness column (consistent-hash rendezvous):
    the tuned profile's hit rate must clear 0.85 on the prefix benchmark
    (was 0.72 without the column; VERDICT r1 weak #5)."""
    tpu = run("tpu", duration=12.0)
    assert tpu.prefix_hit_rate >= 0.85
    assert tpu.slo_attainment >= 0.95


def test_slo_admission_predictor_beats_heuristic_on_hetero_fleet():
    """VERDICT r1 #5: a workload where the predictor EARNS its weight.
    Heterogeneous fleet + tight SLO: predictive SLO admission must deliver
    more goodput at HIGHER SLO attainment than the heuristic-only blend
    (full-scale numbers in bench_slo.py / docs/BENCH_NOTES.md)."""
    from bench_slo import run_pair

    off, on = run_pair(duration_s=20.0, seed=0)
    assert on.shed > 0  # admission actually engaged
    assert on.slo_attainment >= off.slo_attainment
    # 1.25x at 20s; the gap widens with duration (1.95x at 30s) as the
    # heuristic's slow-pod queues compound.
    assert on.goodput_tokens_per_s > off.goodput_tokens_per_s * 1.15
