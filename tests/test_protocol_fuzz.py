"""ext-proc protocol fuzz: arbitrary message sequences, malformed bodies,
and odd orderings must produce clean protocol outcomes (responses,
ExtProcError) — never an unhandled exception or a hang."""

import random

import pytest
from google.protobuf import struct_pb2

from gie_tpu.datastore import Datastore
from gie_tpu.datastore.objects import EndpointPool
from gie_tpu.extproc import RoundRobinPicker, StreamingServer, metadata as mdkeys, pb
from gie_tpu.extproc.server import ExtProcError
from tests.test_datastore import make_pod
from tests.test_extproc import FakeStream


def make_server(h2c: bool = False) -> StreamingServer:
    ds = Datastore()
    ds.pool_set(EndpointPool(
        {"app": "x"}, [8000], "default",
        app_protocol="kubernetes.io/h2c" if h2c else "http"))
    for i in range(3):
        ds.pod_update_or_add(make_pod(name=f"p{i}", ip=f"10.0.0.{i}"))
    return StreamingServer(ds, RoundRobinPicker())


def random_message(rng: random.Random) -> pb.ProcessingRequest:
    choice = rng.random()
    if choice < 0.3:
        hm = pb.HeaderMap()
        for _ in range(rng.randint(0, 4)):
            key = rng.choice([
                "content-type", mdkeys.TEST_ENDPOINT_SELECTION_HEADER,
                mdkeys.OBJECTIVE_KEY, mdkeys.MODEL_NAME_REWRITE_KEY,
                "x-random", "",
            ])
            value = rng.choice([
                b"", b"10.0.0.1", b"\xff\xfe garbage", b"critical",
                bytes(rng.randbytes(rng.randint(0, 40))),
            ])
            hm.headers.append(pb.HeaderValue(key=key, raw_value=value))
        return pb.ProcessingRequest(request_headers=pb.HttpHeaders(
            headers=hm, end_of_stream=rng.random() < 0.5))
    if choice < 0.6:
        body = rng.choice([
            b"", b"{not json", b'{"model": 3}', b"\x00" * rng.randint(0, 100),
            b'{"model": "m", "prompt": "x", "stream": true}',
            bytes(rng.randbytes(rng.randint(0, 200))),
        ])
        return pb.ProcessingRequest(request_body=pb.HttpBody(
            body=body, end_of_stream=rng.random() < 0.5))
    if choice < 0.8:
        req = pb.ProcessingRequest(response_headers=pb.HttpHeaders())
        if rng.random() < 0.5:
            st = struct_pb2.Struct()
            st.fields[mdkeys.DESTINATION_ENDPOINT_SERVED_KEY].string_value = (
                rng.choice(["10.0.0.1:8000", "bogus", ""]))
            req.metadata_context.filter_metadata[
                rng.choice([mdkeys.DESTINATION_ENDPOINT_NAMESPACE, "other"])
            ].CopyFrom(st)
        return req
    return pb.ProcessingRequest(response_body=pb.HttpBody(
        body=bytes(rng.randbytes(rng.randint(0, 64))),
        end_of_stream=rng.random() < 0.5))


@pytest.mark.parametrize("seed", range(6))
@pytest.mark.parametrize("h2c", [False, True])
def test_random_message_sequences_never_crash(seed, h2c):
    rng = random.Random(seed * 2 + int(h2c))
    srv = make_server(h2c=h2c)
    for _ in range(40):
        msgs = [random_message(rng) for _ in range(rng.randint(1, 6))]
        stream = FakeStream(msgs)
        try:
            srv.process(stream)
        except ExtProcError:
            pass  # clean protocol errors are legitimate outcomes
        # Every emitted response must be a well-formed ProcessingResponse.
        for resp in stream.sent:
            assert resp.WhichOneof("response") is not None


def test_duplicate_headers_messages_tolerated():
    """A misbehaving data plane sending two header phases must not corrupt
    the stream (second parse overwrites candidates; no crash)."""
    srv = make_server()
    hm = pb.HeaderMap()
    msg = pb.ProcessingRequest(
        request_headers=pb.HttpHeaders(headers=hm, end_of_stream=True))
    stream = FakeStream([msg, msg])
    srv.process(stream)
    assert len(stream.sent) == 2
