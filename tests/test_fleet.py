"""gie-fleet test suite (ISSUE 18, docs/FLEET.md).

Four tiers:

  cells      bounded cell-index construction — per-cell means over valid
             slots only, the LoRA residency bloom, dead-cell masking.
  compress   gather/scatter round-trips: covering selection is the
             identity permutation, recycled prefix rows clear FLEET-wide,
             compact<->broadcast presence crossing the exact/sketch
             boundary, fleet_resize_state's four transitions.
  recall     the seeded coarse-recall property: the dense cycle's argmax
             endpoint's cell appears in the request's top-K candidate
             list — monotone in K, exact at covering K.
  parity     the keystone: with K covering every cell, the hierarchical
             cycle is BITWISE the dense cycle — matrix over mesh size
             {1, 2, 4, 8} x picker {topk, sinkhorn, random} x ragged M,
             including carried state across waves (non-pallas configs:
             the pallas sinkhorn matches XLA only to atol, by design).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gie_tpu.fleet import (
    FleetPicker,
    broadcast_presence,
    build_cell_rows,
    coarse_total,
    compact_presence,
    fleet_cycle,
    select_cells,
)
from gie_tpu.fleet.compress import (
    gather_vec,
    gather_words,
    global_slots,
    scatter_vec,
    scatter_words,
)
from gie_tpu.fleet.picker import _is_sketch, fleet_resize_state
from gie_tpu.parallel.mesh import make_mesh
from gie_tpu.sched import Scheduler
from gie_tpu.sched import constants as C
from gie_tpu.sched.profile import ProfileConfig, scheduling_cycle
from gie_tpu.sched.types import PrefixTable, SchedState, Weights
from gie_tpu.utils.testing import make_endpoints, make_requests


def _prompts(n, wave=0, families=4, reps=30):
    return [b"S%d " % (i % families) * reps + b"w%d q%d" % (wave, i)
            for i in range(n)]


# ==========================================================================
# cells: bounded index construction
# ==========================================================================


def test_cell_rows_means_over_valid_slots_only():
    m_live, cap = 40, 32          # cell 0 full, cell 1 holds 8 of 32
    queue = np.arange(m_live, dtype=np.float32)
    kv = np.linspace(0.1, 0.9, m_live).astype(np.float32)
    eps = make_endpoints(
        m_live, queue=queue.tolist(), kv=kv.tolist(), m_slots=64)
    load = jnp.asarray(np.arange(64, dtype=np.float32))
    rows = build_cell_rows(eps, load, cell_cap=cap)
    assert rows.queue.shape == (2,)
    np.testing.assert_allclose(rows.n_valid, [32.0, 8.0])
    np.testing.assert_allclose(
        rows.queue, [queue[:32].mean(), queue[32:].mean()], rtol=1e-6)
    np.testing.assert_allclose(
        rows.kv, [kv[:32].mean(), kv[32:].mean()], rtol=1e-6)
    # Load means divide by the VALID population, not cell_cap — dead
    # slots carry load 0 but must not dilute the cell's signal.
    np.testing.assert_allclose(
        rows.load,
        [np.arange(32).mean(), np.arange(32, 40).sum() / 8.0], rtol=1e-6)
    assert bool(rows.valid[0]) and bool(rows.valid[1])


def test_cell_rows_dead_cell_masked():
    eps = make_endpoints(32, queue=[1.0] * 32, m_slots=64)
    rows = build_cell_rows(eps, jnp.zeros(64), cell_cap=32)
    assert bool(rows.valid[0]) and not bool(rows.valid[1])
    assert float(rows.n_valid[1]) == 0.0


def test_cell_rows_lora_residency_bloom():
    eps = make_endpoints(
        64, max_lora=8,
        lora_active=[[5]] + [[]] * 63, m_slots=64)
    rows = build_cell_rows(eps, jnp.zeros(64), cell_cap=32)
    assert int(rows.lora[0]) & (1 << 5)
    assert int(rows.lora[1]) == 0


# ==========================================================================
# compress: gathers, scatters, presence crossings
# ==========================================================================


def test_covering_selection_is_identity_regardless_of_scores():
    cells, cap = 4, 32
    m = cells * cap
    rng = np.random.default_rng(0)
    eps = make_endpoints(m, queue=rng.integers(0, 9, m).tolist(),
                         m_slots=m)
    reqs = make_requests(8, prompts=_prompts(8), m_slots=m)
    rows = build_cell_rows(eps, jnp.zeros(m), cell_cap=cap)
    coarse = jnp.asarray(
        rng.standard_normal((8, cells)), jnp.float32) * 1e3
    sel, cand, _scores = select_cells(
        coarse, rows, reqs, eps, cell_cap=cap, k=cells)
    np.testing.assert_array_equal(np.asarray(sel), np.arange(cells))
    assert cand.shape == (8, cells)
    # And the gather built from it is the identity slot map.
    np.testing.assert_array_equal(
        np.asarray(global_slots(sel, cell_cap=cap, m_c=m)), np.arange(m))


def test_gather_scatter_vec_roundtrip_with_padding():
    cap = 32
    sel = jnp.asarray([1, 3], jnp.int32)
    gslots = global_slots(sel, cell_cap=cap, m_c=C.M_BUCKETS[0])
    assert gslots.shape == (64,)
    full = jnp.asarray(np.arange(128, dtype=np.float32))
    comp = gather_vec(full, gslots, fill=-7.0)
    np.testing.assert_array_equal(np.asarray(comp[:32]),
                                  np.arange(32, 64))
    np.testing.assert_array_equal(np.asarray(comp[32:]),
                                  np.arange(96, 128))
    back = scatter_vec(full * 0.0, gslots, comp + 1.0)
    expect = np.zeros(128, np.float32)
    expect[32:64] = np.arange(32, 64) + 1
    expect[96:128] = np.arange(96, 128) + 1
    np.testing.assert_array_equal(np.asarray(back), expect)


def test_scatter_words_clears_recycled_rows_fleet_wide():
    cap, m = 32, 128
    p_slots = 4
    present = jnp.asarray(
        np.full((p_slots, m // 32), 0xFFFF_FFFF, np.uint32))
    sel = jnp.asarray([1, 3], jnp.int32)
    comp = gather_words(present, sel, cell_cap=cap, m_c=64)
    assert comp.shape == (p_slots, 2)
    new_cols = jnp.zeros_like(comp).at[0, :].set(jnp.uint32(0x1))
    # Row 1's key was recycled by the compressed insert: its OLD bits —
    # including the ones in cells 0 and 2 the gather never touched —
    # must clear, or a new chunk key inherits a stale endpoint set.
    differ = jnp.asarray([False, True, False, False])
    out = np.asarray(scatter_words(
        present, sel, new_cols, differ, cell_cap=cap))
    assert out[1, 0] == 0 and out[1, 2] == 0          # cleared fleet-wide
    assert out[1, 1] == 0 and out[1, 3] == 0          # took new cols
    assert out[0, 0] == 0xFFFF_FFFF                    # untouched cells
    assert out[0, 1] == 0x1 and out[0, 3] == 0x1       # gathered cols land
    assert (out[2:] [:, [0, 2]] == 0xFFFF_FFFF).all()


def test_compact_broadcast_presence_roundtrip():
    rng = np.random.default_rng(1)
    m, cap = 128, 32
    cells = m // cap
    dense = jnp.asarray(
        rng.integers(0, 2**32, (8, m // 32), dtype=np.uint32))
    # 4 source cells word-align up to a 32-cell sketch axis.
    cell_bits = compact_presence(dense, cell_cap=cap, out_cells=32)
    assert cell_bits.shape == (8, 1)
    back = broadcast_presence(
        cell_bits, jnp.arange(cells, dtype=jnp.int32),
        cell_cap=cap, m_c=m)
    # Broadcast is the warm superset: every member of a warm cell warm.
    assert (np.asarray(back) & np.asarray(dense) == np.asarray(dense)).all()
    # And compacting the broadcast is a fixed point.
    np.testing.assert_array_equal(
        np.asarray(compact_presence(back, cell_cap=cap, out_cells=32)),
        np.asarray(cell_bits))


def test_fleet_resize_state_four_transitions():
    cap = 32
    exact = SchedState.init(m=64)
    exact = exact.replace(
        assumed_load=jnp.arange(64, dtype=jnp.float32),
        prefix=exact.prefix.replace(
            keys=exact.prefix.keys.at[0].set(jnp.uint32(0xABC)),
            present=exact.prefix.present.at[0, 1].set(
                jnp.uint32(1 << 3))))   # slot 35 holds chunk 0xABC

    # exact -> exact: the dense migration.
    up = fleet_resize_state(exact, m=256, cell_cap=cap)
    assert not _is_sketch(up)
    np.testing.assert_array_equal(
        np.asarray(up.assumed_load[:64]), np.arange(64))

    # exact -> sketch: surviving endpoints keep cluster-grain affinity.
    sk = fleet_resize_state(exact, m=2048, cell_cap=cap)
    assert _is_sketch(sk)
    cells = 2048 // cap
    assert sk.prefix.present.shape[1] == cells // 32
    word = int(np.asarray(sk.prefix.present)[0, 0])
    assert word & (1 << 1)             # slot 35 -> cell 1 bit survives
    np.testing.assert_array_equal(
        np.asarray(sk.assumed_load[:64]), np.arange(64))

    # sketch -> sketch: cell axis pads (still a multiple of 32).
    sk2 = fleet_resize_state(sk, m=4096, cell_cap=cap)
    assert _is_sketch(sk2)
    assert int(np.asarray(sk2.prefix.present)[0, 0]) & (1 << 1)

    # sketch -> exact: every member of a warm cell starts warm.
    down = fleet_resize_state(sk, m=64, cell_cap=cap)
    assert not _is_sketch(down)
    row = np.asarray(down.prefix.present)[0]
    assert row[1] == 0xFFFF_FFFF       # cell 1's members all warm
    assert row[0] == 0


# ==========================================================================
# recall: the coarse stage finds the dense argmax's cell
# ==========================================================================


def test_coarse_recall_monotone_and_exact_at_covering_k():
    """The property the coarse stage exists for: a cell is a cluster, so
    load is CORRELATED within a cell — per-cell base queue/kv plus small
    within-cell jitter (an i.i.d.-uniform fleet has no cell structure and
    the cell mean says nothing about the cell max; that regime is covered
    by the covering-K parity contract instead). Each request carries a
    subset hint spanning 4 of the 8 cells, so the dense winner — and the
    eligibility-masked candidate list — varies per request."""
    cap = 32
    m = 256                            # 8 cells, a real M bucket
    cells = m // cap
    n = 64
    rng = np.random.default_rng(42)
    base_q = np.asarray([2.0, 34.0, 10.0, 28.0, 6.0, 38.0, 18.0, 26.0])
    base_kv = np.asarray([0.1, 0.8, 0.3, 0.7, 0.15, 0.85, 0.5, 0.6])
    queue = (np.repeat(base_q, cap)
             + rng.uniform(0.0, 4.0, m)).astype(np.float32)
    kv = np.clip(np.repeat(base_kv, cap)
                 + rng.uniform(0.0, 0.05, m), 0.0, 0.95).astype(np.float32)
    eps = make_endpoints(m, queue=queue.tolist(), kv=kv.tolist(),
                         m_slots=m)
    subsets = []
    for _ in range(n):
        allowed = rng.choice(cells, size=4, replace=False)
        subsets.append(
            [int(c) * cap + s for c in allowed for s in range(cap)])
    reqs = make_requests(n, prompts=_prompts(n), subset=subsets,
                         m_slots=m)
    weights = Weights.default()
    cfg = ProfileConfig()
    state = SchedState.init(m=m)

    res, _ = jax.jit(functools.partial(
        scheduling_cycle, cfg=cfg, predictor_fn=None))(
            state, reqs, eps, weights, jax.random.PRNGKey(7), None)
    primary = np.asarray(res.indices)[:, 0]
    picked = primary >= 0
    assert picked.sum() > 32, "storm of unpicked rows — vacuous"
    true_cell = primary[picked] // cap
    assert len(np.unique(true_cell)) > 1, "degenerate: one winner cell"

    rows = build_cell_rows(eps, state.assumed_load, cell_cap=cap)
    coarse = coarse_total(
        rows, jnp.zeros((n, cells), jnp.float32), reqs, weights,
        queue_norm=cfg.queue_norm, load_norm=cfg.load_norm)
    recalls = []
    for k in range(1, cells + 1):
        _sel, cand, _sc = select_cells(
            coarse, rows, reqs, eps, cell_cap=cap, k=k)
        hit = (np.asarray(cand)[picked] == true_cell[:, None]).any(axis=1)
        recalls.append(float(hit.mean()))
    assert recalls == sorted(recalls), recalls      # monotone in K
    assert recalls[-1] == 1.0, recalls              # covering K is exact
    # Seeded floors: with cell-correlated load the winner's cell leads
    # the candidate list almost immediately.
    assert recalls[0] >= 0.9, recalls
    assert recalls[1] == 1.0, recalls


# ==========================================================================
# parity: covering K == bitwise dense, across the deployment matrix
# ==========================================================================


@pytest.mark.parametrize("mesh_size", [1, 2, 4, 8])
@pytest.mark.parametrize("picker", ["topk", "sinkhorn"])
def test_fleet_parity_matrix_covering_k(mesh_size, picker):
    """Scheduler(mesh) vs FleetPicker(mesh) on a ragged fleet (41 live
    endpoints on the 64 bucket) with K covering both cells: indices,
    status, scores, and carried state must be ARRAY-EQUAL across two
    state-carrying waves. Non-pallas configs only — the pallas sinkhorn
    matches XLA to atol, not bitwise."""
    assert len(jax.devices()) >= 8
    cfg = ProfileConfig(picker=picker)
    mesh = make_mesh(mesh_size) if mesh_size > 1 else None
    rng = np.random.default_rng(11)
    m = 41
    eps = make_endpoints(
        m,
        queue=rng.integers(0, 30, m).tolist(),
        kv=rng.uniform(0, 0.9, m).tolist(),
        m_slots=64)
    dense = Scheduler(cfg, seed=5, mesh=mesh)
    fleet = FleetPicker(cfg, seed=5, mesh=mesh, topk=2, cell_cap=32)
    for wave in range(2):
        reqs = make_requests(24, prompts=_prompts(24, wave=wave),
                             m_slots=64)
        r1 = dense.pick(reqs, eps)
        r2 = fleet.pick(reqs, eps)
        np.testing.assert_array_equal(
            np.asarray(r1.indices), np.asarray(r2.indices))
        np.testing.assert_array_equal(
            np.asarray(r1.status), np.asarray(r2.status))
        np.testing.assert_array_equal(
            np.asarray(r1.scores), np.asarray(r2.scores))
    np.testing.assert_array_equal(
        dense.snapshot_assumed_load(), fleet.snapshot_assumed_load())
    np.testing.assert_array_equal(
        np.asarray(dense.state.prefix.keys),
        np.asarray(fleet.state.prefix.keys))
    np.testing.assert_array_equal(
        np.asarray(dense.state.prefix.present),
        np.asarray(fleet.state.prefix.present))


def test_fleet_parity_random_picker_and_aux_provenance():
    """The random picker threads the SAME rng key through both paths;
    the fleet result additionally carries per-request candidate-cell
    provenance with in-range cells and finite scores."""
    cfg = ProfileConfig(picker="random")
    dense = Scheduler(cfg, seed=9)
    fleet = FleetPicker(cfg, seed=9, topk=2, cell_cap=32)
    eps = make_endpoints(64, queue=list(range(64)), m_slots=64)
    reqs = make_requests(16, prompts=_prompts(16), m_slots=64)
    r1 = dense.pick(reqs, eps)
    r2 = fleet.pick(reqs, eps)
    np.testing.assert_array_equal(
        np.asarray(r1.indices), np.asarray(r2.indices))
    assert r2.fleet is not None
    cand = np.asarray(r2.fleet.cells)
    assert cand.shape == (16, 2)
    assert ((cand >= 0) & (cand < 2)).all()
    assert np.isfinite(np.asarray(r2.fleet.scores)).all()
    assert r1.fleet is None            # dense path carries no fleet aux


def test_fleet_sketch_mode_serves_every_picker():
    """Past the largest dense bucket (m=2048 > M_MAX): sketch-state
    picks land on live global slots for every picker, the compression
    ratio reflects the candidate block, and the event paths (complete /
    evict / clear-prefix) stay serviceable."""
    m, cap, topk = 2048, 64, 4
    rng = np.random.default_rng(3)
    eps = make_endpoints(
        m,
        queue=rng.integers(0, 30, m).tolist(),
        kv=rng.uniform(0, 0.9, m).tolist(),
        m_slots=m)
    for picker in ("topk", "sinkhorn", "random"):
        sched = FleetPicker(
            ProfileConfig(picker=picker), seed=2, topk=topk, cell_cap=cap)
        reqs = make_requests(16, prompts=_prompts(16), m_slots=m)
        res = sched.pick(reqs, eps)
        primary = np.asarray(res.indices)[:, 0]
        ok = primary >= 0
        assert ok.any()
        assert (primary[ok] < m).all()
        assert _is_sketch(sched.state)
        assert sched.compression_ratio(m) == pytest.approx(
            (topk * cap) / m)
        sched.complete(int(primary[ok][0]), 1.0)
        sched.evict_endpoint(int(primary[ok][0]))
        sched.clear_prefix_endpoint(3)          # sketch no-op, no raise
        report = sched.fleet_report()
        assert report["mode"] == "sketch"
        assert report["cells"] == m // cap


def test_fleet_picker_validation_and_report():
    with pytest.raises(ValueError):
        FleetPicker(cell_cap=31)
    with pytest.raises(ValueError):
        FleetPicker(topk=0)
    with pytest.raises(ValueError):
        FleetPicker(topk=64, cell_cap=1024)    # block exceeds M_BUCKETS[-1]
    sched = FleetPicker(topk=2, cell_cap=32)
    report = sched.debug_report()
    assert report["fleet"]["topk"] == 2
    fr = sched.fleet_report()
    assert fr["waves"] == 0 and fr["mode"] == "exact"


def test_affinity_columns_recorded_on_pick():
    """Schema-v2 provenance (gie-learn residual): every picked row
    carries the device-gathered prefix/session columns of its CHOSEN
    endpoint; unpicked rows stay zero; record_affinity=False drops the
    leaf entirely (pytree-stable None, like prefill)."""
    sched = Scheduler(ProfileConfig(), seed=1)
    eps = make_endpoints(8, queue=list(range(8)))
    reqs = make_requests(6, prompts=_prompts(6))
    res = sched.pick(reqs, eps)
    aff = np.asarray(res.affinity)
    assert aff.shape == (6, 2)
    assert np.isfinite(aff).all()
    primary = np.asarray(res.indices)[:, 0]
    assert (aff[primary < 0] == 0.0).all()
    off = Scheduler(ProfileConfig(record_affinity=False), seed=1)
    assert off.pick(reqs, eps).affinity is None


def test_fleet_options_validation():
    from gie_tpu.runtime.options import Options

    Options(pool_name="p", fleet_topk=4, fleet_cell_cap=64).validate()
    with pytest.raises(ValueError):
        Options(pool_name="p", fleet_topk=4, fleet_cell_cap=33).validate()
    with pytest.raises(ValueError):
        Options(pool_name="p", fleet_topk=64,
                fleet_cell_cap=1024).validate()
    assert Options(pool_name="p").fleet_topk == 0    # default off
