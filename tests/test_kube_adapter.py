"""Kubernetes adapter translation tests (pure functions; no cluster)."""

import pytest

from gie_tpu.api import types as api
from gie_tpu.api.types import pool_from_dict
from gie_tpu.controller.kube import (
    KubeClusterClient,
    pod_from_k8s,
    watch_event_from_k8s,
)


def test_pod_from_k8s_dict():
    pod = pod_from_k8s({
        "metadata": {
            "name": "vllm-0", "namespace": "inference",
            "labels": {"app": "vllm"},
            "annotations": {"inference.networking.k8s.io/active-ports": "8000"},
        },
        "status": {
            "podIP": "10.4.2.1",
            "conditions": [
                {"type": "Initialized", "status": "True"},
                {"type": "Ready", "status": "True"},
            ],
        },
    })
    assert pod.name == "vllm-0" and pod.namespace == "inference"
    assert pod.ip == "10.4.2.1" and pod.ready
    assert pod.annotations["inference.networking.k8s.io/active-ports"] == "8000"


def test_pod_not_ready_without_ready_condition():
    pod = pod_from_k8s({
        "metadata": {"name": "p", "namespace": "d"},
        "status": {"podIP": "1.2.3.4",
                   "conditions": [{"type": "Ready", "status": "False"}]},
    })
    assert not pod.ready
    pod2 = pod_from_k8s({"metadata": {"name": "p"}, "status": {}})
    assert not pod2.ready and pod2.ip == ""


def test_pool_from_k8s_manifest():
    pool = pool_from_dict({
        "apiVersion": "inference.networking.k8s.io/v1",
        "kind": "InferencePool",
        "metadata": {"name": "my-pool", "namespace": "inference"},
        "spec": {
            "selector": {"matchLabels": {"app": "vllm"}},
            "targetPorts": [{"number": 8000}, {"number": 8002}],
            "endpointPickerRef": {"name": "epp", "port": {"number": 9002},
                                  "failureMode": "FailOpen"},
        },
    })
    pool.validate()
    assert pool.metadata.name == "my-pool"
    assert [p.number for p in pool.spec.targetPorts] == [8000, 8002]
    assert pool.spec.endpointPickerRef.failureMode == "FailOpen"


def test_watch_event_translation():
    ev = watch_event_from_k8s(
        {"type": "ADDED",
         "object": {"metadata": {"name": "p1", "namespace": "ns"}}},
        "Pod",
    )
    assert (ev.type, ev.kind, ev.namespace, ev.name) == ("ADDED", "Pod", "ns", "p1")


def test_pod_from_k8s_snake_case_to_dict_shape():
    """The kubernetes client's .to_dict() emits snake_case keys — IP and
    deletion timestamp must survive."""
    pod = pod_from_k8s({
        "metadata": {"name": "p", "namespace": "n",
                     "deletion_timestamp": "2026-01-01T00:00:00Z"},
        "status": {"pod_ip": "10.9.9.9",
                   "conditions": [{"type": "Ready", "status": "True"}]},
    })
    assert pod.ip == "10.9.9.9"
    assert pod.deletionTimestamp == "2026-01-01T00:00:00Z"
    assert pod.ready


def test_client_requires_some_configuration():
    """Stdlib-HTTP client: constructing with neither an explicit server,
    a kubeconfig, nor an in-cluster service account is a clear error."""
    with pytest.raises(RuntimeError, match="no usable Kubernetes"):
        KubeClusterClient("default", "pool")


# ---- status writes (VERDICT r1 #7: real-cluster parent conditions) --------


class FakeCustomObjectsApi:
    """Duck-typed stand-in for kubernetes CustomObjectsApi (the same
    technique the watch-path tests use)."""

    def __init__(self):
        self.patches: list = []

    def patch_namespaced_custom_object_status(
            self, group, version, namespace, plural, name, body):
        self.patches.append(
            dict(group=group, version=version, namespace=namespace,
                 plural=plural, name=name, body=body))
        return body


def _pool_with_epp(epp_name="epp-svc"):
    return api.InferencePool(
        metadata=api.ObjectMeta(name="pool", namespace="ns"),
        spec=api.InferencePoolSpec(
            selector=api.LabelSelector(matchLabels={"app": "m"}),
            targetPorts=[api.Port(8000)],
            endpointPickerRef=api.EndpointPickerRef(
                name=epp_name, port=api.Port(9002)),
        ),
    )


def test_patch_pool_status_subresource_shape():
    from gie_tpu.controller.kube import patch_pool_status

    fake = FakeCustomObjectsApi()
    status = api.InferencePoolStatus(parents=[])
    ps = api.ParentStatus(parentRef=api.ParentReference(name="gw"))
    ps.set_condition(api.Condition(
        api.COND_ACCEPTED, "True", api.REASON_ACCEPTED, "ok"))
    status.parents.append(ps)
    patch_pool_status(fake, "ns", "pool", status)
    assert len(fake.patches) == 1
    p = fake.patches[0]
    assert (p["group"], p["version"], p["plural"], p["name"]) == (
        api.GROUP, "v1", "inferencepools", "pool")
    parent = p["body"]["status"]["parents"][0]
    assert parent["parentRef"]["name"] == "gw"
    cond = parent["conditions"][0]
    assert cond["type"] == "Accepted" and cond["status"] == "True"
    # metav1.Condition requires lastTransitionTime: stamped at the patch
    # boundary when the computation left it empty.
    assert cond["lastTransitionTime"].endswith("Z")
    # Empties pruned like pool_to_dict (no namespace="" keys etc.).
    assert "namespace" not in parent["parentRef"]


def test_pool_status_controller_publishes_conditions():
    from gie_tpu.controller.kube import patch_pool_status
    from gie_tpu.controller.status import PoolStatusController

    class FakeClient:
        def __init__(self, pool, services):
            self.pool = pool
            self.services = services
            self.custom = FakeCustomObjectsApi()

        def get_pool(self, ns, name):
            return self.pool

        def patch_pool_status(self, ns, name, status):
            patch_pool_status(self.custom, ns, name, status)

    client = FakeClient(_pool_with_epp(), services={("ns", "epp-svc")})
    ctrl = PoolStatusController(
        client, "ns", "pool", parents=["gw-a", "gw-b"],
        service_exists=lambda ns, name: (ns, name) in client.services)
    assert ctrl.reconcile()
    body = client.custom.patches[-1]["body"]["status"]
    assert [p["parentRef"]["name"] for p in body["parents"]] == [
        "gw-a", "gw-b"]
    for parent in body["parents"]:
        conds = {c["type"]: c for c in parent["conditions"]}
        assert conds["Accepted"]["status"] == "True"
        assert conds["ResolvedRefs"]["status"] == "True"

    # No transition -> no patch (metav1.Condition lastTransitionTime moves
    # only on status change; unchanged reconciles must not churn
    # resourceVersion).
    n_before = len(client.custom.patches)
    assert ctrl.reconcile()
    assert len(client.custom.patches) == n_before

    # EPP Service missing -> ResolvedRefs False / InvalidExtensionRef
    # (reference inferencepool_types.go:321-347 reason set).
    client.services.clear()
    ctrl.reconcile()
    body = client.custom.patches[-1]["body"]["status"]
    conds = {c["type"]: c for c in body["parents"][0]["conditions"]}
    assert conds["ResolvedRefs"]["status"] == "False"
    assert conds["ResolvedRefs"]["reason"] == api.REASON_INVALID_EXTENSION_REF

    # Pool gone -> no patch, returns False.
    client.pool = None
    n = len(client.custom.patches)
    assert not ctrl.reconcile()
    assert len(client.custom.patches) == n


def test_status_controller_preserves_export_entry():
    """The export controller's InferencePoolImport parent entry must
    survive gateway-status reconciliation (shared merge semantics with the
    harness)."""
    from gie_tpu.controller.status import PoolStatusController

    pool = _pool_with_epp()
    exp = api.ParentStatus(parentRef=api.ParentReference(
        name="pool", namespace="ns", group=api.GROUP_X,
        kind="InferencePoolImport"))
    exp.set_condition(api.Condition(
        api.COND_EXPORTED, "True", api.REASON_EXPORTED, "exported"))
    pool.status.parents.append(exp)

    captured = {}

    class FakeClient:
        def get_pool(self, ns, name):
            return pool

        def patch_pool_status(self, ns, name, status):
            captured["status"] = status

    ctrl = PoolStatusController(
        FakeClient(), "ns", "pool", parents=["gw"],
        service_exists=lambda ns, name: True)
    assert ctrl.reconcile()
    kinds = [p.parentRef.kind for p in captured["status"].parents]
    assert "InferencePoolImport" in kinds
    names = [p.parentRef.name for p in captured["status"].parents]
    assert "gw" in names


def test_kubeconfig_inline_data_fields(tmp_path):
    """kind/minikube/GKE kubeconfigs embed base64 *-data instead of file
    paths; the adapter must honor them (CA in memory, client pair
    materialized 0600)."""
    import base64
    import os
    import stat

    import yaml

    from gie_tpu.controller.kube import _load_kubeconfig

    ca_pem = (
        "-----BEGIN CERTIFICATE-----\nZmFrZQ==\n-----END CERTIFICATE-----\n")
    cfg = {
        "current-context": "c",
        "contexts": [{"name": "c",
                      "context": {"cluster": "cl", "user": "u"}}],
        "clusters": [{"name": "cl", "cluster": {
            "server": "https://1.2.3.4:6443",
            "certificate-authority-data":
                base64.b64encode(ca_pem.encode()).decode(),
        }}],
        "users": [{"name": "u", "user": {
            "client-certificate-data":
                base64.b64encode(b"CERTPEM").decode(),
            "client-key-data": base64.b64encode(b"KEYPEM").decode(),
        }}],
    }
    p = tmp_path / "kubeconfig"
    p.write_text(yaml.safe_dump(cfg))
    (server, token, ca_file, ca_data, client_cert,
     insecure) = _load_kubeconfig(str(p))
    assert server == "https://1.2.3.4:6443"
    assert token is None and ca_file is None and insecure is False
    assert ca_data == ca_pem
    crt, key = client_cert
    assert open(crt, "rb").read() == b"CERTPEM"
    assert open(key, "rb").read() == b"KEYPEM"
    for f in (crt, key):
        assert stat.S_IMODE(os.stat(f).st_mode) == 0o600
    assert stat.S_IMODE(os.stat(os.path.dirname(crt)).st_mode) == 0o700


def test_kubeconfig_exec_plugin_is_a_clear_error(tmp_path):
    import yaml

    from gie_tpu.controller.kube import _load_kubeconfig

    cfg = {
        "current-context": "c",
        "contexts": [{"name": "c",
                      "context": {"cluster": "cl", "user": "u"}}],
        "clusters": [{"name": "cl",
                      "cluster": {"server": "https://1.2.3.4:6443"}}],
        "users": [{"name": "u", "user": {
            "exec": {"command": "gke-gcloud-auth-plugin"}}}],
    }
    p = tmp_path / "kubeconfig"
    p.write_text(yaml.safe_dump(cfg))
    with pytest.raises(RuntimeError, match="exec/auth-provider"):
        _load_kubeconfig(str(p))
