"""Kubernetes adapter translation tests (pure functions; no cluster)."""

import pytest

from gie_tpu.api.types import pool_from_dict
from gie_tpu.controller.kube import (
    KubeClusterClient,
    pod_from_k8s,
    watch_event_from_k8s,
)


def test_pod_from_k8s_dict():
    pod = pod_from_k8s({
        "metadata": {
            "name": "vllm-0", "namespace": "inference",
            "labels": {"app": "vllm"},
            "annotations": {"inference.networking.k8s.io/active-ports": "8000"},
        },
        "status": {
            "podIP": "10.4.2.1",
            "conditions": [
                {"type": "Initialized", "status": "True"},
                {"type": "Ready", "status": "True"},
            ],
        },
    })
    assert pod.name == "vllm-0" and pod.namespace == "inference"
    assert pod.ip == "10.4.2.1" and pod.ready
    assert pod.annotations["inference.networking.k8s.io/active-ports"] == "8000"


def test_pod_not_ready_without_ready_condition():
    pod = pod_from_k8s({
        "metadata": {"name": "p", "namespace": "d"},
        "status": {"podIP": "1.2.3.4",
                   "conditions": [{"type": "Ready", "status": "False"}]},
    })
    assert not pod.ready
    pod2 = pod_from_k8s({"metadata": {"name": "p"}, "status": {}})
    assert not pod2.ready and pod2.ip == ""


def test_pool_from_k8s_manifest():
    pool = pool_from_dict({
        "apiVersion": "inference.networking.k8s.io/v1",
        "kind": "InferencePool",
        "metadata": {"name": "my-pool", "namespace": "inference"},
        "spec": {
            "selector": {"matchLabels": {"app": "vllm"}},
            "targetPorts": [{"number": 8000}, {"number": 8002}],
            "endpointPickerRef": {"name": "epp", "port": {"number": 9002},
                                  "failureMode": "FailOpen"},
        },
    })
    pool.validate()
    assert pool.metadata.name == "my-pool"
    assert [p.number for p in pool.spec.targetPorts] == [8000, 8002]
    assert pool.spec.endpointPickerRef.failureMode == "FailOpen"


def test_watch_event_translation():
    ev = watch_event_from_k8s(
        {"type": "ADDED",
         "object": {"metadata": {"name": "p1", "namespace": "ns"}}},
        "Pod",
    )
    assert (ev.type, ev.kind, ev.namespace, ev.name) == ("ADDED", "Pod", "ns", "p1")


def test_pod_from_k8s_snake_case_to_dict_shape():
    """The kubernetes client's .to_dict() emits snake_case keys — IP and
    deletion timestamp must survive."""
    pod = pod_from_k8s({
        "metadata": {"name": "p", "namespace": "n",
                     "deletion_timestamp": "2026-01-01T00:00:00Z"},
        "status": {"pod_ip": "10.9.9.9",
                   "conditions": [{"type": "Ready", "status": "True"}]},
    })
    assert pod.ip == "10.9.9.9"
    assert pod.deletionTimestamp == "2026-01-01T00:00:00Z"
    assert pod.ready


def test_client_requires_kubernetes_package():
    import importlib.util

    if importlib.util.find_spec("kubernetes") is not None:
        pytest.skip("kubernetes installed; ImportError branch unreachable")
    with pytest.raises(ImportError, match="kubernetes"):
        KubeClusterClient("default", "pool")
